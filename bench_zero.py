"""ZeRO-1 weight-update sharding benchmark (--zero1, worker/zero.py).

Measures the three claims of the sharded weight update on an N-device
data-parallel mesh:

  1. **memory** — per-device optimizer-state bytes, sharded vs
     replicated, from the live state's actual shard placement (the
     ~(N-1)/N reduction that is the point of ZeRO-1);
  2. **throughput** — steps/s, zero1 vs replicated, INTERLEAVED timed
     blocks (per-step K=1 and fused windows K=8) so machine-load drift
     lands on both legs equally; each block closes with a value fetch
     (the only real fence on this session's relay);
  3. **exactness** — same-seed losses bit-identical with zero1 on vs
     off at K=1 and K=8, and an in-process elastic churn drill: Adam
     moments bit-exact through a live N -> N/2 device-to-device
     re-partition, and a same-size world re-form mid-run continuing
     the no-churn trajectory bit-for-bit at equal step count.

Honest annotation: on CPU the collectives are loopback memcpy and the
jitted step shares cores with the host loop, so the throughput ratio
is a parity check (the acceptance gate is +/-5%), not the TPU story —
there, reduce-scatter + 1/N update + all-gather reclaims both memory
and update-compute time.  The JSON carries the platform.

Prints exactly one JSON line.
"""

import json
import os
import sys
import time

# CPU fallback gets a virtual 8-device mesh; inert for real TPU
# backends (the flag only affects the host platform).  Must be set
# before jax imports.
_FLAGS = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _FLAGS:
    os.environ["XLA_FLAGS"] = (
        _FLAGS + " --xla_force_host_platform_device_count=8"
    ).strip()


def _trainer(spec, mesh, batch_size, zero1, seed, accum=1):
    from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer

    return CollectiveTrainer(
        spec, batch_size=batch_size, mesh=mesh, rng_seed=seed,
        zero1=zero1, accum_steps=accum,
    )


def run_bench(blocks=5, steps_per_block=40, fused_steps=8,
              batch_size=8, bit_steps=40):
    import jax

    if os.environ.get("ELASTICDL_TPU_PLATFORM"):
        jax.config.update(
            "jax_platforms", os.environ["ELASTICDL_TPU_PLATFORM"]
        )
    import numpy as np
    from jax.sharding import Mesh

    import bench as _bench  # provenance helpers
    from elasticdl_tpu.models import mnist

    platform = jax.devices()[0].platform
    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("data",))
    spec = mnist.model_spec(learning_rate=1e-3)
    xs, ys = mnist.synthetic_data(n=batch_size * n * 8, seed=0)
    per = batch_size * n
    data = [(xs[i * per:(i + 1) * per], ys[i * per:(i + 1) * per])
            for i in range(8)]

    # ---- 1. memory: live per-device optimizer-state bytes ----------------
    rep_t = _trainer(spec, mesh, batch_size, False, 0)
    z1_t = _trainer(spec, mesh, batch_size, True, 0)
    mem_rep = rep_t.zero1_report()
    mem_z1 = z1_t.zero1_report()
    reduction = 1.0 - (
        mem_z1["per_device_bytes"] / mem_rep["per_device_bytes"]
    )
    # The gate: >= (N-1)/N up to the irreducible replicated remainder
    # (Adam's scalar step count + pad tail; < 0.01% of the state here).
    target = (n - 1) / n
    memory_ok = mem_z1["per_device_bytes"] <= (
        mem_rep["per_device_bytes"] / n * 1.01
    )

    # ---- 3a. exactness: same-seed bit-identity, K=1 and K=8 --------------
    losses_rep = [float(rep_t.train_minibatch(*data[i % 8])[0])
                  for i in range(bit_steps)]
    losses_z1 = [float(z1_t.train_minibatch(*data[i % 8])[0])
                 for i in range(bit_steps)]
    bitwise_k1 = losses_rep == losses_z1
    max_diff_k1 = float(np.max(np.abs(
        np.asarray(losses_rep) - np.asarray(losses_z1)
    )))

    rep_w = _trainer(spec, mesh, batch_size, False, 1)
    z1_w = _trainer(spec, mesh, batch_size, True, 1)
    wl_rep, wl_z1 = [], []
    for w in range(3):
        pb = [rep_w.prepare_batch(*data[(w * fused_steps + i) % 8])
              for i in range(fused_steps)]
        pz = [z1_w.prepare_batch(*data[(w * fused_steps + i) % 8])
              for i in range(fused_steps)]
        wl_rep.append(np.asarray(
            rep_w.train_window(rep_w.stage_window(pb))[0]))
        wl_z1.append(np.asarray(
            z1_w.train_window(z1_w.stage_window(pz))[0]))
    bitwise_k8 = all(
        np.array_equal(a, b) for a, b in zip(wl_rep, wl_z1)
    )
    max_diff_k8 = float(max(
        np.max(np.abs(a - b)) for a, b in zip(wl_rep, wl_z1)
    ))

    # ---- 2. throughput: interleaved blocks -------------------------------
    def per_step_block(trainer, k0):
        t0 = time.perf_counter()
        for k in range(steps_per_block):
            loss, _ = trainer.train_minibatch(*data[(k0 + k) % 8])
        float(loss)  # fence: close the block with a value fetch
        return time.perf_counter() - t0

    def window_block(trainer, k0):
        t0 = time.perf_counter()
        losses = None
        for w in range(steps_per_block // fused_steps):
            prepared = [
                trainer.prepare_batch(
                    *data[(k0 + w * fused_steps + i) % 8]
                )
                for i in range(fused_steps)
            ]
            losses, _ = trainer.train_window(
                trainer.stage_window(prepared)
            )
        np.asarray(losses)  # fence
        return time.perf_counter() - t0

    # One untimed warm block per leg first (the box takes ~a minute to
    # reach steady state — page cache, thread pools, frequency), then
    # interleaved timed blocks with the LEG ORDER alternating per block
    # so any residual monotonic drift cancels instead of crediting
    # whichever leg runs second.
    per_step_block(rep_t, 0), per_step_block(z1_t, 0)
    window_block(rep_w, 0), window_block(z1_w, 0)
    pairs_k1, pairs_k8 = [], []
    for b in range(blocks):
        k0 = b * steps_per_block
        legs_k1 = [(rep_t, 0), (z1_t, 1)]
        legs_k8 = [(rep_w, 0), (z1_w, 1)]
        if b % 2:
            legs_k1.reverse()
            legs_k8.reverse()
        row = [None, None]
        for trainer, idx in legs_k1:
            row[idx] = round(per_step_block(trainer, k0) * 1000.0, 2)
        pairs_k1.append(row)
        row = [None, None]
        for trainer, idx in legs_k8:
            row[idx] = round(window_block(trainer, k0) * 1000.0, 2)
        pairs_k8.append(row)
    total_steps = blocks * steps_per_block

    def sps(pairs, idx):
        return total_steps / (sum(p[idx] for p in pairs) / 1000.0)

    def median_ratio(pairs):
        # Per-block replicated/zero1 time ratio, median over blocks:
        # robust to the load spikes a shared CI box injects into
        # individual blocks (each pair ran back-to-back, so a spike
        # hits both legs of ITS block roughly equally; the median
        # discards blocks where it didn't).
        ratios = sorted(p[0] / p[1] for p in pairs)
        mid = len(ratios) // 2
        if len(ratios) % 2:
            return ratios[mid]
        return (ratios[mid - 1] + ratios[mid]) / 2.0

    ratio_k1 = median_ratio(pairs_k1)
    ratio_k8 = median_ratio(pairs_k8)

    # ---- 3b. elastic churn: repartition + same-size re-form --------------
    churn = _trainer(spec, mesh, batch_size, True, 2)
    nochurn = _trainer(spec, mesh, batch_size, True, 2)
    ref_losses = [float(nochurn.train_minibatch(*data[i % 8])[0])
                  for i in range(10)]
    churn_losses = [float(churn.train_minibatch(*data[i % 8])[0])
                    for i in range(5)]
    t0 = time.perf_counter()
    churn.rebuild(mesh)  # same-size world re-form (peer replaced)
    reform_ms = (time.perf_counter() - t0) * 1000.0
    churn_losses += [float(churn.train_minibatch(*data[i % 8])[0])
                     for i in range(5, 10)]
    reform_bitwise = churn_losses == ref_losses

    moments_ok = None
    resize_ms = None
    if n >= 2:
        half = Mesh(np.array(devices[: n // 2]), ("data",))
        before = churn._opt_state_on_host()
        t0 = time.perf_counter()
        churn.rebuild(half)  # N -> N/2, live device-to-device
        resize_ms = (time.perf_counter() - t0) * 1000.0
        after = churn._opt_state_on_host()
        moments_ok = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(before),
                            jax.tree_util.tree_leaves(after))
        )
    counters = churn.timing.counters()

    return {
        "metric": "zero1_update_sharding",
        "value": round(mem_rep["per_device_bytes"]
                       / mem_z1["per_device_bytes"], 3),
        "unit": "x per-device optimizer-state bytes vs replicated "
                "(%d devices)" % n,
        "vs_baseline": None,
        "detail": {
            "platform": platform,
            "num_devices": n,
            "memory": {
                "replicated_bytes_per_device":
                    mem_rep["per_device_bytes"],
                "zero1_bytes_per_device": mem_z1["per_device_bytes"],
                "reduction": round(reduction, 6),
                "target_reduction": round(target, 6),
                "meets_target_within_1pct": memory_ok,
                "padding_bytes": mem_z1["padding_bytes"],
                "scalar_leaves_replicated":
                    mem_z1["scalar_leaves_replicated"],
            },
            "throughput": {
                "per_step_ratio_zero1_vs_replicated":
                    round(ratio_k1, 4),
                "fused_k%d_ratio_zero1_vs_replicated" % fused_steps:
                    round(ratio_k8, 4),
                "ratio_is": "median over per-block steps/s ratios "
                            "(load-spike robust)",
                "aggregate_per_step_ratio": round(
                    sps(pairs_k1, 1) / sps(pairs_k1, 0), 4),
                "aggregate_fused_ratio": round(
                    sps(pairs_k8, 1) / sps(pairs_k8, 0), 4),
                # One-sided gate: zero1 must not cost steps/s (>= 0.95
                # of replicated).  Being FASTER is expected — the
                # replicated path redundantly applies the full update
                # on all N devices, the sharded path does 1/N each.
                "within_5pct": ratio_k1 >= 0.95 and ratio_k8 >= 0.95,
                "samples": {
                    "per_step_pairs": pairs_k1,
                    "fused_pairs": pairs_k8,
                    "format": "[replicated_ms, zero1_ms] per "
                              "interleaved block of %d steps"
                              % steps_per_block,
                },
            },
            "exactness": {
                "bitwise_k1": bitwise_k1,
                "bitwise_k%d" % fused_steps: bitwise_k8,
                "loss_max_abs_diff_k1": max_diff_k1,
                "loss_max_abs_diff_k%d" % fused_steps: max_diff_k8,
                "bit_steps": bit_steps,
            },
            "elastic": {
                "same_size_reform_trajectory_bitwise": reform_bitwise,
                "reform_ms": round(reform_ms, 1),
                "resize_to_half_moments_bitwise": moments_ok,
                "resize_ms": round(resize_ms, 1)
                if resize_ms is not None else None,
                "zero1_reshard_bytes":
                    counters.get("zero1_reshard_bytes", 0),
                "host_fallbacks":
                    counters.get("reshard_host_fallbacks", 0),
            },
            "timing_zero1": z1_t.timing.summary().get("zero1", {}),
            "note": (
                "CPU capture: collectives are loopback memcpy, so the "
                "throughput ratio is a parity check; the TPU regime "
                "(reduce-scatter + 1/N update + all-gather over ICI) "
                "is where the update-compute win lands"
                if platform == "cpu" else
                "TPU capture: sharded update over ICI"
            ),
            "device": _bench._device_fingerprint(jax),
            "env": _bench._env_snapshot(),
        },
    }


def main():
    t0 = time.monotonic()
    result = run_bench()
    result["detail"]["bench_wall_secs"] = round(
        time.monotonic() - t0, 1
    )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
