"""Closed-loop online-learning drill: trainer -> aggregation tier ->
fleet, all elastic at once (ROADMAP item 4, docs/serving.md "The
online loop").

One process hosts the control plane, real subprocesses do the serving:

 - a REAL CollectiveTrainer (mnist spec) trains continuously and its
   ``--export_steps`` hook lands versioned servables at the SOURCE
   base (atomic publish, program traced once and reused);
 - the ModelAggregator ingests them, EMA-aggregates over a window, and
   publishes complete servables at the FLEET base on the freshness
   SLO; each publish is driven through the router — a plain barrier
   rollout, except one mid-run publish that goes CANARY-first: p% of
   the key ring on canary replicas, soak, promote barrier-clean;
 - serving replicas are SUBPROCESSES spawned/drained by the
   FleetAutoscaler off the router's own telemetry: a zipf workload
   phase pushes queue wait over the breach threshold (>= 1 grow), a
   light phase lets it idle (>= 1 shrink down the SIGTERM
   graceful-drain path);
 - closed-loop zipf clients hammer ``:predict`` through the router the
   whole time and record every response's ``model_version`` stamp.

Everything is asserted FROM OUTSIDE — response stamps and /metrics:

 - 0 dropped/errored requests and 0 mixed-version keys (per-key
   ``model_version`` monotone) across >= 3 aggregator-driven publishes
   riding live traffic;
 - >= 1 autoscaler grow and >= 1 shrink (router.scale_up/scale_down
   counters), with every admitted request completing;
 - the canary cohort serves ~p% of keyed traffic during its soak
   (cohort counters diffed around the soak) and is promoted
   barrier-clean;
 - measured publish freshness meets the configured SLO
   (elasticdl_agg_freshness_seconds on the router's /metrics, and the
   aggregator's slo_misses counter stays 0).

Run: python bench_online.py [--load_secs 50 --light_secs 40]
Exit code 0 = all gates passed; the result JSON is printed either way.
"""

import http.client
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("ELASTICDL_TPU_PLATFORM", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

FEATURES = 128             # model wide enough that device execute —
HIDDEN = 768               # not the HTTP shell — saturates the
CLASSES = 8                # executor under the load phase
ROWS_PER_REQUEST = 4
EXPORT_STEPS = 40          # trainer steps per servable export
STEP_SLEEP = 0.06          # paces exports to one every ~4s
AGG_WINDOW = 3
PUBLISH_INTERVAL = 8.0     # publish throttle (each publish = rollout)
FRESHNESS_SLO = 25.0       # = throttle + scan cadence + margin
EXPORT_KEEP = 4
CANARY_FRACTION = 0.3
CANARY_SOAK = 8.0
ZIPF_KEYS = 400
ZIPF_EXPONENT = 1.05
LOAD_CONCURRENCY = 8
LIGHT_CONCURRENCY = 1
LIGHT_THINK_SECS = 0.15
SCALE_UP_QUEUE_MS = 10.0
SCALE_DOWN_QUEUE_MS = 3.0
BREACH_SECS = 2.0
IDLE_SECS = 6.0
COOLDOWN_SECS = 10.0
MAX_REPLICAS = 3


def _zipf_weights(n, a):
    ranks = np.arange(1, n + 1, dtype=np.float64)
    weights = ranks ** -a
    return weights / weights.sum()


class _Recorder:
    """Per-key model_version sequences + drop accounting, shared by
    every client thread."""

    def __init__(self):
        self.lock = threading.Lock()
        self.versions = {}
        self.errors = []
        self.total = 0

    def note(self, key, version):
        with self.lock:
            self.versions.setdefault(key, []).append(version)
            self.total += 1

    def note_error(self, detail):
        with self.lock:
            self.errors.append(detail)

    def mixed_keys(self):
        with self.lock:
            return [key for key, seen in self.versions.items()
                    if seen != sorted(seen)]

    def distinct_versions(self):
        with self.lock:
            return sorted({v for seen in self.versions.values()
                           for v in seen})


def _workload_phase(port, recorder, keys, weights, concurrency,
                    duration, think_secs=0.0, seed=0):
    """Closed-loop keyed clients for ``duration`` seconds."""
    stop_at = time.monotonic() + duration

    # Request rows serialized ONCE — per-request JSON cost stays on
    # the wire, not in this process's hot loop.
    rows = [[round((r * FEATURES + c) % 17 / 17.0, 3)
             for c in range(FEATURES)] for r in range(ROWS_PER_REQUEST)]
    instances_json = json.dumps(rows)

    def client(idx):
        rng = np.random.RandomState(seed * 1000 + idx)
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=60)
        try:
            while time.monotonic() < stop_at:
                key = keys[rng.choice(len(keys), p=weights)]
                body = ('{"instances": %s, "routing_key": "%s"}'
                        % (instances_json, key))
                try:
                    conn.request("POST", "/v1/models/mlp:predict",
                                 body=body)
                    resp = conn.getresponse()
                    payload = resp.read()
                except OSError as e:
                    recorder.note_error("transport: %r" % (e,))
                    conn.close()
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=60)
                    continue
                if resp.status != 200:
                    recorder.note_error(
                        (resp.status,
                         payload[:160].decode("utf-8", "replace")))
                else:
                    recorder.note(
                        key, json.loads(payload)["model_version"])
                if think_secs:
                    time.sleep(think_secs)
        finally:
            conn.close()

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _trainer_loop(trainer, xs, ys, stop):
    while not stop.is_set():
        trainer.train_minibatch(xs, ys)
        stop.wait(STEP_SLEEP)
    trainer.flush_checkpoints()


def _metrics(port):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        return conn.getresponse().read().decode()
    finally:
        conn.close()


def _metric_value(text, name):
    for line in text.splitlines():
        if line.startswith(name) and (line[len(name)] in " {"):
            return float(line.rsplit(" ", 1)[1])
    return None


def _wait(predicate, timeout, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _drill_spec():
    """A CTR-ranking-shaped MLP, wide enough that one batch's device
    execute dominates its HTTP shell on this rig — the regime where
    queue wait is a real load signal."""
    import jax
    import optax

    from elasticdl_tpu.models.mlp import mlp_apply, mlp_init
    from elasticdl_tpu.models.spec import ModelSpec

    sizes = [FEATURES, HIDDEN, HIDDEN, CLASSES]

    def loss_fn(outputs, labels):
        return optax.softmax_cross_entropy(
            outputs, jax.nn.one_hot(labels, CLASSES))

    return ModelSpec(
        name="mlp",
        init_fn=lambda rng: mlp_init(rng, sizes),
        apply_fn=lambda params, x, train=False: mlp_apply(params, x),
        loss_fn=loss_fn,
        optimizer=optax.adam(1e-3),
        feed=lambda records: records,
    )


def run_drill(load_secs, light_secs):
    from elasticdl_tpu.aggregation import ModelAggregator
    from elasticdl_tpu.serving.export import ContinuousExporter
    from elasticdl_tpu.serving.fleet import (
        FleetAutoscaler,
        ProcessReplicaSpawner,
        canary_slice,
    )
    from elasticdl_tpu.serving.router import (
        Router,
        build_router_server,
    )
    from elasticdl_tpu.worker.collective_trainer import (
        CollectiveTrainer,
    )

    tmp = tempfile.mkdtemp(prefix="bench_online_")
    src = os.path.join(tmp, "trainer_exports")
    pub = os.path.join(tmp, "fleet_exports")

    # -- trainer tier --------------------------------------------------
    spec = _drill_spec()
    exporter = ContinuousExporter(src, model_name="mlp",
                                  platforms=("cpu",))
    trainer = CollectiveTrainer(spec, batch_size=16,
                                exporter=exporter,
                                export_steps=EXPORT_STEPS)
    rng = np.random.RandomState(0)
    xs = rng.rand(16, FEATURES).astype(np.float32)
    ys = rng.randint(0, CLASSES, 16)
    stop = threading.Event()
    trainer_thread = threading.Thread(
        target=_trainer_loop, args=(trainer, xs, ys, stop),
        daemon=True)
    trainer_thread.start()

    # -- aggregation tier ----------------------------------------------
    agg = ModelAggregator(
        src, pub, window=AGG_WINDOW, mode="ema", ema_decay=0.5,
        freshness_slo_secs=FRESHNESS_SLO,
        min_publish_interval_secs=PUBLISH_INTERVAL,
        export_keep=EXPORT_KEEP, model_name="mlp")
    assert _wait(lambda: agg.ingest_once() or
                 agg.stats()["last_ingested_version"], 60), (
        "trainer never exported")
    first_version, _ = agg.publish()

    # -- serving fleet -------------------------------------------------
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "ELASTICDL_TPU_PLATFORM": "cpu",
                "OMP_NUM_THREADS": "1",
                "OPENBLAS_NUM_THREADS": "1"})
    # An unfillable batch size + a real window: under CONCURRENT load
    # every request waits ~the window for companions (the batcher's
    # pressure-aware flush), a lone client pays zero — so the windowed
    # queue-wait signal tracks concurrency pressure even on a rig
    # where the model itself can't saturate a core.
    spawner = ProcessReplicaSpawner(
        pub, extra_args=["--max_batch_size", "64",
                         "--batch_timeout_ms", "30"], env=env)
    first_addr = spawner.spawn(boot_version=first_version)
    # probe_timeout rides 1-core compile storms (a replica warming a
    # fresh version can stall its /statz answer for seconds here).
    router = Router([first_addr], export_dir=pub,
                    probe_interval=0.25, probe_timeout=5.0,
                    poll_interval=0.5, auto_rollout=False)
    server = build_router_server(router, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    router.start(coordinate=True)
    autoscaler = FleetAutoscaler(
        router, spawner, min_replicas=1, max_replicas=MAX_REPLICAS,
        scale_up_queue_ms=SCALE_UP_QUEUE_MS,
        scale_down_queue_ms=SCALE_DOWN_QUEUE_MS,
        breach_secs=BREACH_SECS,
        idle_secs=IDLE_SECS, cooldown_secs=COOLDOWN_SECS,
        cadence_secs=0.5)
    assert _wait(lambda: router.coordinator.committed_version
                 == first_version
                 and len(router.state.routable(first_version)) >= 1,
                 90), router.fleet_status()
    autoscaler.start()

    # -- aggregation control loop (publish -> rollout/canary -> GC) ----
    canary_report = {}

    def agg_loop():
        while not stop.is_set():
            agg.ingest_once()
            if agg.publish_due():
                version, freshness = agg.publish()
                committed = router.coordinator.committed_version
                routable = len(router.state.routable(committed))
                if not canary_report and routable >= 2:
                    before = router.cohort_stats()
                    started = router.start_canary(
                        version, CANARY_FRACTION,
                        freshness_seconds=freshness)
                    if started.get("started"):
                        stop.wait(CANARY_SOAK)
                        after = router.cohort_stats()
                        promoted = router.promote_canary()
                        keyed = {
                            c: (after[c]["keyed_requests"]
                                - before[c]["keyed_requests"])
                            for c in ("canary", "baseline")}
                        total = sum(keyed.values())
                        canary_report.update({
                            "version": version,
                            "fraction": CANARY_FRACTION,
                            "soak_keyed_requests": keyed,
                            "measured_traffic_share":
                                round(keyed["canary"] / total, 4)
                                if total else None,
                            "promoted":
                                bool(promoted.get("promoted")),
                        })
                    else:
                        router.external_rollout(
                            version, freshness_seconds=freshness)
                else:
                    router.external_rollout(
                        version, freshness_seconds=freshness)
                agg.gc_published(
                    router.coordinator.committed_version)
            stop.wait(0.5)

    agg_thread = threading.Thread(target=agg_loop, daemon=True)
    agg_thread.start()

    # -- workload ------------------------------------------------------
    recorder = _Recorder()
    keys = ["user-%d" % i for i in range(ZIPF_KEYS)]
    weights = _zipf_weights(ZIPF_KEYS, ZIPF_EXPONENT)
    t0 = time.monotonic()
    _workload_phase(port, recorder, keys, weights,
                    LOAD_CONCURRENCY, load_secs, seed=1)
    _workload_phase(port, recorder, keys, weights,
                    LIGHT_CONCURRENCY, light_secs,
                    think_secs=LIGHT_THINK_SECS, seed=2)
    # Tail: give a pending shrink time to drain, keep a trickle going.
    _workload_phase(port, recorder, keys, weights, 1, 8.0,
                    think_secs=0.2, seed=3)
    elapsed = time.monotonic() - t0

    metrics_text = _metrics(port)
    stop.set()
    agg_thread.join(timeout=30)
    trainer_thread.join(timeout=30)
    autoscaler.stop()
    agg_stats = agg.stats()
    status = router.fleet_status()
    router.stop()
    server.shutdown()
    server.server_close()
    spawner.close()

    # -- gates (all from response stamps + /metrics) -------------------
    expected_share = float(sum(
        w for key, w in zip(keys, weights)
        if canary_slice(key) < CANARY_FRACTION))
    scale_up = _metric_value(
        metrics_text,
        'elasticdl_fleet_router_counter{name="router.scale_up"}') or 0
    scale_down = _metric_value(
        metrics_text,
        'elasticdl_fleet_router_counter{name="router.scale_down"}'
    ) or 0
    freshness_metric = _metric_value(
        metrics_text, "elasticdl_agg_freshness_seconds")
    mixed = recorder.mixed_keys()
    versions_seen = recorder.distinct_versions()
    share = canary_report.get("measured_traffic_share")
    gates = {
        "zero_drops": len(recorder.errors) == 0,
        "zero_mixed_version_keys": len(mixed) == 0,
        "rode_3_publishes": len(versions_seen) >= 3,
        "autoscaler_grew": scale_up >= 1,
        "autoscaler_shrank": scale_down >= 1,
        "canary_promoted": bool(canary_report.get("promoted")),
        "canary_share_near_p": (
            share is not None
            and abs(share - expected_share) <= 0.15),
        "freshness_met_slo": (
            freshness_metric is not None
            and freshness_metric <= FRESHNESS_SLO
            and agg_stats["counters"].get("slo_misses", 0) == 0),
    }
    result = {
        "metric": "online_loop_drill",
        "value": int(all(gates.values())),
        "unit": "all gates passed (1/0)",
        "vs_baseline": None,
        "detail": {
            "gates": gates,
            "elapsed_secs": round(elapsed, 1),
            "requests": recorder.total,
            "dropped_or_errored": recorder.errors[:5],
            "distinct_versions_served": versions_seen,
            "mixed_version_keys": mixed[:5],
            "publishes": agg_stats["counters"].get("published", 0),
            "ingested_exports": agg_stats["counters"].get(
                "ingested", 0),
            "freshness_seconds": freshness_metric,
            "freshness_slo_secs": FRESHNESS_SLO,
            "slo_misses": agg_stats["counters"].get("slo_misses", 0),
            "scale_up_events": scale_up,
            "scale_down_events": scale_down,
            "canary": dict(canary_report,
                           expected_traffic_share=round(
                               expected_share, 4)),
            "final_committed_version":
                status["committed_version"],
            "final_replicas": sorted(status["replicas"]),
            "n_cpus": len(os.sched_getaffinity(0)),
        },
    }
    return result


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser("bench_online")
    parser.add_argument("--load_secs", type=float, default=50.0,
                        help="heavy zipf phase (drives the scale-up)")
    parser.add_argument("--light_secs", type=float, default=40.0,
                        help="light phase (drives the scale-down)")
    args = parser.parse_args(argv)
    result = run_drill(args.load_secs, args.light_secs)
    print(json.dumps(result, indent=2))
    return 0 if result["value"] else 1


if __name__ == "__main__":
    sys.exit(main())
