"""DeepFM steps/sec over the full parameter-server path.

The sparse-CTR benchmark named in BASELINE.json (the reference's async-PS
benchmark role, docs/benchmark/report_cn.md): a worker trains DeepFM
through 2 real PS shard subprocesses — gRPC push/pull, tensor codec,
id-mod-N sharding, native C++ optimizer kernels — end to end.  Each
"step" is one minibatch: pull dense params, pull unique embedding rows,
jitted fwd/bwd, push dense+sparse gradients.

The reference publishes no absolute DeepFM steps/sec (report_cn is a
scaling study), so ``vs_baseline`` is null; the absolute number and its
breakdown are the artifact.

Prints exactly one JSON line.
"""

import json
import os
import subprocess
import sys
import time

# The PS path is host-side (numpy + C++ kernels + gRPC) and the worker's
# jitted step is tiny, so this bench runs on CPU and never depends on the
# TPU relay.  Force it: the session shell exports JAX_PLATFORMS=axon, so
# a setdefault would silently aim the worker at the relay (and hang when
# the relay is wedged).  Override with ELASTICDL_TPU_PLATFORM to test
# another platform deliberately.
_PLATFORM = os.environ.get("ELASTICDL_TPU_PLATFORM") or "cpu"
os.environ["ELASTICDL_TPU_PLATFORM"] = _PLATFORM
os.environ["JAX_PLATFORMS"] = _PLATFORM


def run_bench(num_ps=2, batch_size=512, vocab_size=100_000,
              num_fields=10, embedding_dim=8, warmup=5, iters=50):
    import jax

    if os.environ.get("ELASTICDL_TPU_PLATFORM"):
        jax.config.update(
            "jax_platforms", os.environ["ELASTICDL_TPU_PLATFORM"]
        )
    import numpy as np

    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.utils import grpc_utils
    from elasticdl_tpu.worker.ps_client import PSClient
    from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer

    ports = [grpc_utils.find_free_port() for _ in range(num_ps)]
    procs = []
    try:
        for i, port in enumerate(ports):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"  # PS is host-side numpy/C++
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "elasticdl_tpu.ps.server",
                 "--port", str(port), "--ps_id", str(i),
                 "--num_ps", str(num_ps),
                 "--opt_type", "adam", "--opt_args",
                 "learning_rate=0.001"],
                env=env,
            ))
        channels = []
        for port in ports:
            ch = grpc_utils.build_channel("localhost:%d" % port)
            grpc_utils.wait_for_channel_ready(ch, timeout=30)
            channels.append(ch)
        client = PSClient(channels)

        spec = deepfm.model_spec(
            num_fields=num_fields, vocab_size=vocab_size,
            embedding_dim=embedding_dim,
        )
        trainer = ParameterServerTrainer(
            spec, client, batch_size=batch_size, get_model_steps=1
        )
        dense, ids, labels = deepfm.synthetic_data(
            n=batch_size * 8, num_fields=num_fields,
            vocab_size=vocab_size, seed=0,
        )
        batches = []
        for s in range(0, len(labels), batch_size):
            records = [
                (dense[j], ids[j], labels[j])
                for j in range(s, s + batch_size)
            ]
            batches.append(spec.feed(records))

        for k in range(warmup):
            trainer.train_minibatch(*batches[k % len(batches)])
        start = time.perf_counter()
        for k in range(iters):
            loss, version = trainer.train_minibatch(
                *batches[k % len(batches)]
            )
        elapsed = time.perf_counter() - start

        steps_per_sec = iters / elapsed
        platform = jax.devices()[0].platform
        return {
            "metric": "deepfm_ps_steps_per_sec",
            "value": round(steps_per_sec, 2),
            "unit": "steps/sec",
            "vs_baseline": None,
            "detail": {
                "platform": platform,
                "num_ps": num_ps,
                "batch_size": batch_size,
                "vocab_size": vocab_size,
                "num_fields": num_fields,
                "embedding_dim": embedding_dim,
                "examples_per_sec": round(steps_per_sec * batch_size, 1),
                "ms_per_step": round(1000.0 * elapsed / iters, 2),
                "last_loss": float(loss),
                "ps_version": int(version),
                "baseline": "reference publishes no absolute DeepFM "
                            "steps/sec (report_cn.md is a scaling "
                            "study)",
            },
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()


def _run_with_watchdog(timeout_secs=None):
    if timeout_secs is None:
        timeout_secs = int(
            os.environ.get("ELASTICDL_BENCH_TIMEOUT", "600")
        )
    stderr_tail = ""
    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--inner"],
            capture_output=True, text=True, timeout=timeout_secs,
        )
        stderr_tail = (proc.stderr or "")[-300:]
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        reason = "no JSON output from measurement subprocess"
    except subprocess.TimeoutExpired:
        reason = "measurement timed out after %ds" % timeout_secs
    except (OSError, json.JSONDecodeError) as e:
        reason = "%s: %s" % (type(e).__name__, e)
    return {
        "metric": "deepfm_ps_steps_per_sec",
        "value": None,
        "unit": "steps/sec",
        "vs_baseline": None,
        "detail": {"error": reason, "stderr_tail": stderr_tail},
    }


if __name__ == "__main__":
    if "--inner" in sys.argv:
        print(json.dumps(run_bench()))
    else:
        print(json.dumps(_run_with_watchdog()))
    sys.exit(0)
