"""DeepFM steps/sec over the full parameter-server path.

The sparse-CTR benchmark named in BASELINE.json (the reference's async-PS
benchmark role, docs/benchmark/report_cn.md): a worker trains DeepFM
through 2 real PS shard subprocesses — gRPC push/pull, tensor codec,
id-mod-N sharding, native C++ optimizer kernels — end to end.  Each
"step" is one minibatch: pull dense params, pull unique embedding rows,
jitted fwd/bwd, push dense+sparse gradients.

The reference publishes no absolute DeepFM steps/sec (report_cn is a
scaling study), so ``vs_baseline`` is null; the absolute number and its
breakdown are the artifact.

Default mode prints exactly one JSON line (single worker).

``--scale`` runs the multi-worker concurrency study (VERDICT r3 #3):
N async worker processes hammer the same PS shards; reports aggregate
examples/s per worker count plus per-phase worker timings.  NOTE this
image pins the whole job — every worker, every PS shard — to ONE cpu
core (nproc=1), so aggregate throughput CANNOT rise with workers here;
what the study shows is (a) correctness and stability under concurrent
pushes, (b) no serialization collapse (aggregate stays ~flat while per-
worker RPC latency absorbs the queueing), and (c) the measured PS
service cost per step, which is what determines workers/shard capacity
on real multi-core hosts (reference analog: the Go PS's 64-stream
server, go/pkg/ps/server.go:233-253).
"""

import json
import os
import subprocess
import sys
import time

# The PS path is host-side (numpy + C++ kernels + gRPC) and the worker's
# jitted step is tiny, so this bench runs on CPU and never depends on the
# TPU relay.  Force it: the session shell exports JAX_PLATFORMS=axon, so
# a setdefault would silently aim the worker at the relay (and hang when
# the relay is wedged).  Override with ELASTICDL_TPU_PLATFORM to test
# another platform deliberately.
_PLATFORM = os.environ.get("ELASTICDL_TPU_PLATFORM") or "cpu"
os.environ["ELASTICDL_TPU_PLATFORM"] = _PLATFORM
os.environ["JAX_PLATFORMS"] = _PLATFORM


def run_bench(num_ps=2, batch_size=512, vocab_size=100_000,
              num_fields=10, embedding_dim=8, warmup=5, iters=50):
    import jax

    if os.environ.get("ELASTICDL_TPU_PLATFORM"):
        jax.config.update(
            "jax_platforms", os.environ["ELASTICDL_TPU_PLATFORM"]
        )
    import numpy as np

    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.utils import grpc_utils
    from elasticdl_tpu.worker.ps_client import PSClient
    from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer

    ports, procs = _start_ps(num_ps)
    try:
        channels = []
        for port in ports:
            ch = grpc_utils.build_channel("localhost:%d" % port)
            grpc_utils.wait_for_channel_ready(ch, timeout=30)
            channels.append(ch)
        client = PSClient(channels)

        spec = deepfm.model_spec(
            num_fields=num_fields, vocab_size=vocab_size,
            embedding_dim=embedding_dim,
        )
        trainer = ParameterServerTrainer(
            spec, client, batch_size=batch_size, get_model_steps=1
        )
        dense, ids, labels = deepfm.synthetic_data(
            n=batch_size * 8, num_fields=num_fields,
            vocab_size=vocab_size, seed=0,
        )
        batches = []
        for s in range(0, len(labels), batch_size):
            records = [
                (dense[j], ids[j], labels[j])
                for j in range(s, s + batch_size)
            ]
            batches.append(spec.feed(records))

        for k in range(warmup):
            trainer.train_minibatch(*batches[k % len(batches)])
        start = time.perf_counter()
        for k in range(iters):
            loss, version = trainer.train_minibatch(
                *batches[k % len(batches)]
            )
        elapsed = time.perf_counter() - start

        steps_per_sec = iters / elapsed
        platform = jax.devices()[0].platform
        return {
            "metric": "deepfm_ps_steps_per_sec",
            "value": round(steps_per_sec, 2),
            "unit": "steps/sec",
            "vs_baseline": None,
            "detail": {
                "platform": platform,
                "num_ps": num_ps,
                "batch_size": batch_size,
                "vocab_size": vocab_size,
                "num_fields": num_fields,
                "embedding_dim": embedding_dim,
                "examples_per_sec": round(steps_per_sec * batch_size, 1),
                "ms_per_step": round(1000.0 * elapsed / iters, 2),
                "last_loss": float(loss),
                "ps_version": int(version),
                "baseline": "reference publishes no absolute DeepFM "
                            "steps/sec (report_cn.md is a scaling "
                            "study)",
            },
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()


def _start_ps(num_ps):
    """Spawn num_ps PS shard subprocesses; returns (ports, procs)."""
    from elasticdl_tpu.utils import grpc_utils

    ports = [grpc_utils.find_free_port() for _ in range(num_ps)]
    procs = []
    for i, port in enumerate(ports):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # PS is host-side numpy/C++
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.ps.server",
             "--port", str(port), "--ps_id", str(i),
             "--num_ps", str(num_ps),
             "--opt_type", "adam", "--opt_args", "learning_rate=0.001"],
            env=env,
        ))
    return ports, procs


def run_worker(ports, batch_size=512, vocab_size=100_000, num_fields=10,
               embedding_dim=8, warmup=3, iters=30, seed=0,
               barrier=None):
    """One concurrent worker: train against EXISTING PS shards, print a
    JSON line with steps, wall-clock window, and per-phase timings."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.utils import grpc_utils
    from elasticdl_tpu.worker.ps_client import PSClient
    from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer

    channels = []
    for port in ports:
        ch = grpc_utils.build_channel("localhost:%d" % port)
        grpc_utils.wait_for_channel_ready(ch, timeout=30)
        channels.append(ch)
    spec = deepfm.model_spec(
        num_fields=num_fields, vocab_size=vocab_size,
        embedding_dim=embedding_dim,
    )
    trainer = ParameterServerTrainer(
        spec, PSClient(channels), batch_size=batch_size,
        get_model_steps=1,
    )
    dense, ids, labels = deepfm.synthetic_data(
        n=batch_size * 4, num_fields=num_fields,
        vocab_size=vocab_size, seed=seed,
    )
    batches = []
    for s in range(0, len(labels), batch_size):
        records = [(dense[j], ids[j], labels[j])
                   for j in range(s, s + batch_size)]
        batches.append(spec.feed(records))
    for k in range(warmup):
        trainer.train_minibatch(*batches[k % len(batches)])
    if barrier:
        # All workers finish warmup (incl. jit compile) BEFORE any
        # measures, so one worker's compile can't pollute another's
        # measured window on this single-core box.
        with open("%s.ready.%d" % (barrier, seed), "w"):
            pass
        # Longer than the coordinator's 600 s ready-deadline, so a fast
        # worker never aborts a run the coordinator still considers live.
        deadline = time.time() + 900
        while not os.path.exists(barrier + ".go"):
            if time.time() > deadline:
                raise RuntimeError("barrier timeout")
            time.sleep(0.05)
    trainer.timing.reset()
    start = time.time()
    loss = version = 0.0
    for k in range(iters):
        loss, version = trainer.train_minibatch(
            *batches[k % len(batches)]
        )
    end = time.time()
    print(json.dumps({
        "steps": iters, "start": start, "end": end,
        "last_loss": float(loss), "ps_version": int(version),
        "timing": {
            name: round(s["total_s"], 3)
            for name, s in trainer.timing.summary().items()
            if "total_s" in s  # skip counter sections (e.g. zero1)
        },
    }))


def run_scale(worker_counts=(1, 2, 4), num_ps=2, batch_size=512,
              iters=60):
    """Aggregate async-PS throughput at 1..N concurrent workers."""
    results = []
    import tempfile

    for n in worker_counts:
        ports, procs = _start_ps(num_ps)
        barrier = os.path.join(
            tempfile.mkdtemp(prefix="edl_scale_"), "barrier")
        workers = []
        try:
            workers = [
                subprocess.Popen(
                    [sys.executable, __file__, "--worker",
                     "--ports", ",".join(map(str, ports)),
                     "--iters", str(iters), "--seed", str(100 + w),
                     "--batch", str(batch_size),
                     "--barrier", barrier],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True,
                )
                for w in range(n)
            ]
            deadline = time.time() + 600
            while sum(
                os.path.exists("%s.ready.%d" % (barrier, 100 + w))
                for w in range(n)
            ) < n:
                if time.time() > deadline:
                    raise RuntimeError("workers never reached barrier")
                if any(w.poll() not in (None, 0) for w in workers):
                    raise RuntimeError("a worker died before barrier")
                time.sleep(0.1)
            with open(barrier + ".go", "w"):
                pass
            from elasticdl_tpu.utils.jsonline import last_json_line

            reports = []
            for w in workers:
                out, _ = w.communicate(timeout=1200)
                report = last_json_line(out)
                if report is not None:
                    reports.append(report)
            if len(reports) < n:
                raise RuntimeError(
                    "only %d/%d workers reported" % (len(reports), n))
            window = (max(r["end"] for r in reports)
                      - min(r["start"] for r in reports))
            total_steps = sum(r["steps"] for r in reports)
            timing = {}
            for r in reports:
                for name, secs in r["timing"].items():
                    timing[name] = timing.get(name, 0.0) + secs
            results.append({
                "workers": n,
                "examples_per_sec": round(
                    total_steps * batch_size / window, 1),
                "steps_per_sec": round(total_steps / window, 2),
                "wall_secs": round(window, 1),
                "mean_step_ms": round(
                    1000.0 * window * n / total_steps, 1),
                "phase_secs_total": {
                    k: round(v, 2) for k, v in sorted(timing.items())
                },
                "last_losses": [
                    round(r["last_loss"], 3) for r in reports
                ],
                "ps_version": max(r["ps_version"] for r in reports),
            })
            print("scale %d workers: %s" % (n, results[-1]),
                  file=sys.stderr, flush=True)
        finally:
            # Workers first (they busy-poll the barrier file), then PS.
            for p in workers + procs:
                if p.poll() is None:
                    p.terminate()
            for p in workers + procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
    base = results[0]["examples_per_sec"]
    out = {
        "metric": "deepfm_ps_scaleout",
        "value": results[-1]["examples_per_sec"],
        "unit": "examples/sec aggregate",
        "vs_baseline": None,
        "detail": {
            "nproc": os.cpu_count(),
            "num_ps": num_ps,
            "batch_size": batch_size,
            "scaling": results,
            "relative": [
                round(r["examples_per_sec"] / base, 3) for r in results
            ],
            "note": "single-core image: flat aggregate == no "
                    "serialization collapse; see BENCHMARKS.md for the "
                    "workers/shard capacity model",
        },
    }
    print(json.dumps(out))
    return out


def run_service_cost(batch_size=512, vocab_size=100_000, num_fields=10,
                     embedding_dim=8, pushes=300):
    """Measure the PS shard's SERIALIZED section directly: decode+apply
    of one worker push, called in-process on the servicer (no gRPC).

    This is the quantity that caps multi-worker scaling per shard on a
    real multi-core host — everything else (worker compute, client
    codec, transport) runs concurrently across cores, but gradient
    apply serializes behind the shard lock.  workers/shard capacity ~=
    worker_step_time / serialized_time_per_push.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.proto import elastic_pb2 as pb
    from elasticdl_tpu.ps.optimizer import create_optimizer
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.utils import tensor_codec
    from elasticdl_tpu.utils.pytree import flatten_with_names, to_numpy

    spec = deepfm.model_spec(
        num_fields=num_fields, vocab_size=vocab_size,
        embedding_dim=embedding_dim,
    )
    named, _ = flatten_with_names(
        to_numpy(spec.init_fn(jax.random.PRNGKey(0))))
    servicer = PserverServicer(
        Parameters(), create_optimizer("adam", "learning_rate=0.001"),
        ps_id=0, num_ps=1,
    )
    servicer.push_model(tensor_codec.model_to_pb(
        dense=named, infos=spec.ps_embedding_infos))

    rng = np.random.RandomState(0)
    dense_bytes = sum(a.nbytes for a in named.values())
    # One full worker minibatch worth of gradients (num_ps=1 -> this
    # shard owns everything): dense grads + unique embedding rows.
    uniq = np.unique(rng.randint(
        0, vocab_size, size=batch_size * num_fields))
    requests = []
    for _ in range(8):  # vary payloads so caches don't flatter the loop
        grads = {n: rng.randn(*a.shape).astype(np.float32)
                 for n, a in named.items()}
        emb = {
            info["name"]: (
                rng.randn(len(uniq), info["dim"]).astype(np.float32),
                uniq,
            )
            for info in spec.ps_embedding_infos
        }
        requests.append(pb.PushGradientsRequest(
            gradients=tensor_codec.model_to_pb(
                dense=grads, embeddings=emb, version=0),
        ))
    for req in requests:  # warm (lazy row init, allocator)
        servicer.push_gradients(req)
    t0 = time.perf_counter()
    for k in range(pushes):
        servicer.push_gradients(requests[k % len(requests)])
    push_ms = 1000.0 * (time.perf_counter() - t0) / pushes

    pull_req = pb.PullEmbeddingVectorsRequest(
        name=spec.ps_embedding_infos[0]["name"], ids=uniq.tolist())
    t0 = time.perf_counter()
    for _ in range(pushes):
        servicer.pull_embedding_vectors(pull_req)
    pull_ms = 1000.0 * (time.perf_counter() - t0) / pushes

    out = {
        "metric": "ps_serialized_service_cost",
        "value": round(push_ms, 3),
        "unit": "ms per push (decode+apply, in-process)",
        "vs_baseline": None,
        "detail": {
            "pull_embedding_ms": round(pull_ms, 3),
            "unique_rows": int(len(uniq)),
            "embedding_dim": embedding_dim,
            "dense_bytes": int(dense_bytes),
            "batch_size": batch_size,
            "pushes": pushes,
            "note": "pull_embedding runs OUTSIDE the shard lock "
                    "(per-row native rw-lock), so only the push cost "
                    "serializes",
        },
    }
    print(json.dumps(out))
    return out


def _run_with_watchdog(timeout_secs=None):
    if timeout_secs is None:
        timeout_secs = int(
            os.environ.get("ELASTICDL_BENCH_TIMEOUT", "600")
        )
    stderr_tail = ""
    try:
        from elasticdl_tpu.utils.jsonline import last_json_line

        proc = subprocess.run(
            [sys.executable, __file__, "--inner"],
            capture_output=True, text=True, timeout=timeout_secs,
        )
        stderr_tail = (proc.stderr or "")[-300:]
        result = last_json_line(proc.stdout)
        if result is not None:
            return result
        reason = "no JSON output from measurement subprocess"
    except subprocess.TimeoutExpired:
        reason = "measurement timed out after %ds" % timeout_secs
    except (OSError, json.JSONDecodeError) as e:
        reason = "%s: %s" % (type(e).__name__, e)
    return {
        "metric": "deepfm_ps_steps_per_sec",
        "value": None,
        "unit": "steps/sec",
        "vs_baseline": None,
        "detail": {"error": reason, "stderr_tail": stderr_tail},
    }


def _argv_int(flag, default):
    if flag in sys.argv:
        return int(sys.argv[sys.argv.index(flag) + 1])
    return default


if __name__ == "__main__":
    if "--worker" in sys.argv:
        ports = [
            int(p) for p in
            sys.argv[sys.argv.index("--ports") + 1].split(",")
        ]
        barrier = None
        if "--barrier" in sys.argv:
            barrier = sys.argv[sys.argv.index("--barrier") + 1]
        run_worker(
            ports,
            batch_size=_argv_int("--batch", 512),
            iters=_argv_int("--iters", 30),
            seed=_argv_int("--seed", 0),
            barrier=barrier,
        )
    elif "--service-cost" in sys.argv:
        run_service_cost(pushes=_argv_int("--pushes", 300))
    elif "--scale" in sys.argv:
        counts = tuple(
            int(c) for c in os.environ.get(
                "ELASTICDL_SCALE_WORKERS", "1,2,4,8").split(",")
        )
        run_scale(worker_counts=counts,
                  iters=_argv_int("--iters", 60))
    elif "--inner" in sys.argv:
        print(json.dumps(run_bench()))
    else:
        print(json.dumps(_run_with_watchdog()))
    sys.exit(0)
