"""Turn bench_results/*.json captures into BENCHMARKS.md-ready rows.

Run after scripts/tpu_window.sh: prints a markdown summary of the
NEWEST capture per stage (older captures are listed by name so none
disappear silently) — headline numbers, the A/B matrix as a table,
per-leg elastic recovery, recorded failure reasons, and the
provenance (device fingerprint, sample spread) a reviewer needs —
paste into BENCHMARKS.md and flip defaults the data supports.

Usage: python scripts/process_bench.py [bench_results_dir]
"""

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from elasticdl_tpu.utils.jsonline import last_json_line  # noqa: E402


def _load(path):
    with open(path) as f:
        return last_json_line(f.read())


def _one_line(text):
    """Markdown-table-safe cell: collapse newlines, escape pipes."""
    return " ".join(str(text).split()).replace("|", "\\|")


def _spread(samples):
    blocks = (samples or {}).get("blocks") or []
    per_iter = [ms / iters for iters, ms in blocks if iters]
    if not per_iter:
        return "n/a"
    return "%.1f-%.1f ms/iter over %d blocks" % (
        min(per_iter), max(per_iter), len(per_iter))


def summarize(results_dir):
    lines = []
    for stage, pattern in (("headline", "headline_*.json"),
                           ("kernels", "kernels_*.json"),
                           ("elastic", "elastic_*.json")):
        paths = sorted(glob.glob(os.path.join(results_dir, pattern)))
        if not paths:
            lines.append("## %s: no captures" % stage)
            continue
        data = _load(paths[-1])
        lines.append("## %s (%s)" % (stage,
                                     os.path.basename(paths[-1])))
        if len(paths) > 1:
            lines.append("  (older captures not shown: %s)" % ", ".join(
                os.path.basename(p) for p in paths[:-1]))
        if data is None:
            lines.append("  unparseable")
            continue
        top_error = data.get("error") or data.get(
            "detail", {}).get("error") if isinstance(
            data.get("detail", {}), dict) else data.get("error")
        if top_error:
            lines.append("- **FAILED**: %s" % _one_line(top_error))
            lines.append("")
            continue
        if stage == "headline":
            det = data.get("detail", {})
            lines.append(
                "- **%s %s** (vs_baseline %s, platform %s)" % (
                    data.get("value"), data.get("unit"),
                    data.get("vs_baseline"),
                    det.get("platform")))
            lines.append("- device: %s" % det.get("device"))
            lines.append("- samples: %s" % _spread(det.get("samples")))
            if det.get("mfu_estimate") is not None:
                lines.append("- MFU estimate: %.1f%%"
                             % (100 * det["mfu_estimate"]))
        elif stage == "kernels":
            rows = data.get("rows", {})
            if rows.get("device"):
                lines.append("- device: %s" % rows["device"])
            for section in ("resnet", "lm", "decode"):
                table = rows.get(section) or {}
                if not table:
                    continue
                lines.append("\n### %s" % section)
                lines.append("| config | result |")
                lines.append("|---|---|")
                for name, row in table.items():
                    if "error" in row:
                        cell = "ERROR: %s" % _one_line(row["error"])
                    else:
                        keep = {k: v for k, v in row.items()
                                if k != "samples"}
                        keep["samples"] = _spread(row.get("samples"))
                        cell = ", ".join(
                            "%s=%s" % kv for kv in keep.items())
                    lines.append("| %s | %s |" % (name, cell))
        else:
            legs = data.get("detail", {}).get("platform_legs", {})
            lines.append("- headline: %s s (leg %s)" % (
                data.get("value"),
                data.get("detail", {}).get("headline_leg")))
            for leg, row in legs.items():
                lines.append("- %s: %s" % (leg, row))
        lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":
    results_dir = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench_results")
    print(summarize(results_dir))
