#!/usr/bin/env bash
# elastic-lint entrypoint: project-native static analysis (EL001-EL004)
# plus a bytecode-compile sweep.  Exits nonzero on any finding — wired
# into scripts/preflight.py and enforced in tier-1 by
# tests/test_elastic_lint.py::test_repo_is_lint_clean.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m tools.elastic_lint elasticdl_tpu tools scripts
python -m compileall -q elasticdl_tpu tools scripts tests
echo "elastic-lint: clean"
