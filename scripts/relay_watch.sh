#!/bin/bash
# Loop: probe the TPU relay with a capped subprocess; exit 0 the moment
# it answers so the caller is notified. Log history to .relay_probe.log.
# NOTE: success = the probe PRINTED its OK line (never trust pipeline rc).
LOG=/root/repo/.relay_probe.log
for i in $(seq 1 200); do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 150 python -c "
import jax
d = jax.devices()
import jax.numpy as jnp
x = jnp.ones((128,128)); v = float((x@x).sum())
print('PROBE-OK', d[0].platform, v, flush=True)
" 2>&1 | grep "PROBE-OK" | head -1)
  echo "$ts probe$i out=[$out]" >> "$LOG"
  if [ -n "$out" ]; then
    echo "RELAY HEALTHY at $ts: $out"
    exit 0
  fi
  sleep 120
done
echo "RELAY never answered"
exit 1
