#!/bin/bash
# Fire the full staged TPU measurement suite on a healthy relay window
# (VERDICT r4 #1/#2/#3/#5).  Each stage is independently budgeted and
# probe-gated, so a relay that wedges mid-window costs one stage, not
# the rest.  Raw JSON lands in bench_results/ for BENCHMARKS.md.
set -u
cd /root/repo
mkdir -p bench_results
ts=$(date -u +%Y%m%dT%H%M%SZ)

echo "== stage 1: headline bench (bench.py) =="
ELASTICDL_BENCH_TOTAL_BUDGET=${HEADLINE_BUDGET:-900} \
  timeout 960 python bench.py \
  > bench_results/headline_$ts.json 2> bench_results/headline_$ts.err
tail -c 600 bench_results/headline_$ts.json; echo

echo "== stage 2: kernel A/B matrix (bench_kernels.py) =="
ELASTICDL_AB_TIMEOUT=${AB_TIMEOUT:-420} \
  timeout 5400 python bench_kernels.py \
  > bench_results/kernels_$ts.json 2> bench_results/kernels_$ts.err
tail -c 600 bench_results/kernels_$ts.json; echo

echo "== stage 3: TPU-inclusive elastic recovery (bench_elastic.py) =="
ELASTICDL_ELASTIC_BENCH_BUDGET=${ELASTIC_BUDGET:-900} \
  timeout 960 python bench_elastic.py \
  > bench_results/elastic_$ts.json 2> bench_results/elastic_$ts.err
tail -c 600 bench_results/elastic_$ts.json; echo

echo "== window done: bench_results/*_$ts.json =="
