#!/usr/bin/env python
"""Regenerate elasticdl_tpu/proto/elastic_pb2.py without protoc.

The image ships no ``protoc`` / ``grpc_tools``, so schema changes can't go
through the normal codegen path.  Instead this script edits the message
schema at the FileDescriptorProto level: it loads the serialized
descriptor already embedded in elastic_pb2.py, applies the declarative
edits in ``EDITS`` (idempotently — rerunning is a no-op), and rewrites
the module in the generated-code layout, recomputing the pure-python
``_serialized_start``/``_serialized_end`` offsets by locating each
message's serialized sub-descriptor inside the new file blob.

Keep ``EDITS`` in sync with proto/elastic.proto (the human-readable
source of truth); this script is how the .proto's schema actually
reaches the runtime.

Usage: python scripts/gen_proto.py [--check]
  --check  exit 1 if elastic_pb2.py would change (CI drift guard)
"""

import os
import re
import sys

from google.protobuf import descriptor_pb2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PB2_PATH = os.path.join(REPO, "elasticdl_tpu", "proto", "elastic_pb2.py")

F = descriptor_pb2.FieldDescriptorProto

# (message name, field name, field number, type, json_name)
EDITS = [
    # bf16-on-the-wire support: `dtype` stays the LOGICAL dtype the
    # decoder must return; `wire_dtype`, when set and different, names
    # the reduced-precision dtype `content` is actually encoded in.
    ("TensorPB", "wire_dtype", 4, F.TYPE_STRING, "wireDtype"),
    # Client asks the PS to encode the pulled rows in this dtype
    # ("" = float32, full fidelity).
    ("PullEmbeddingVectorsRequest", "wire_dtype", 3, F.TYPE_STRING,
     "wireDtype"),
    # Version tag on straggler-safe evaluation reports: the master
    # drops metrics from an already-finished evaluation job instead of
    # folding them into the next job's creation window.
    ("ReportEvaluationMetricsRequest", "model_version", 4, F.TYPE_INT32,
     "modelVersion"),
    # PS restart-generation fencing (docs/ps_recovery.md): every pull/
    # push on the PS data plane carries the serving incarnation; pushes
    # stamped by a dead incarnation are rejected, pulls from a client
    # that observed an older incarnation bypass the version fast path.
    ("PullDenseParametersRequest", "generation", 2, F.TYPE_INT64,
     "generation"),
    ("PullDenseParametersResponse", "generation", 4, F.TYPE_INT64,
     "generation"),
    ("PushGradientsRequest", "generation", 3, F.TYPE_INT64,
     "generation"),
    ("PushGradientsResponse", "generation", 3, F.TYPE_INT64,
     "generation"),
    ("PrepareGradientsRequest", "generation", 4, F.TYPE_INT64,
     "generation"),
    # PS shards tag their version reports with recovery state; the
    # cross-shard min of durable_version is the coordinated-checkpoint
    # commit mark the master (and drills) can read.
    ("ReportVersionRequest", "is_ps", 2, F.TYPE_BOOL, "isPs"),
    ("ReportVersionRequest", "ps_id", 3, F.TYPE_INT32, "psId"),
    ("ReportVersionRequest", "generation", 4, F.TYPE_INT64,
     "generation"),
    ("ReportVersionRequest", "durable_version", 5, F.TYPE_INT32,
     "durableVersion"),
    # Serving-tier PS-backed embedding lookups (docs/serving.md fleet
    # section): read_only pulls never lazily initialize absent rows —
    # serving traffic must not grow the training table — and the
    # response TensorPB is stamped with the shard's restart generation
    # so an embedding-only client (the serving hot-row cache) learns
    # about a PS crash-restore rollback first-class from every lookup
    # and can invalidate rows read from the dead incarnation.
    ("PullEmbeddingVectorsRequest", "read_only", 4, F.TYPE_BOOL,
     "readOnly"),
    ("TensorPB", "generation", 5, F.TYPE_INT64, "generation"),
    # Telemetry piggybacked on the coalesced progress RPC
    # (docs/observability.md): the worker's live steps/s, blocked-on-
    # device fraction (Timing.sync_fraction), PS push-pipeline depth,
    # and mean fused-window size ride the report the worker already
    # sends every window, so the master's per-job aggregation — the
    # future resize controller's sensor input — costs zero extra RPCs.
    ("ReportBatchDoneRequest", "steps_per_sec", 3, F.TYPE_DOUBLE,
     "stepsPerSec"),
    ("ReportBatchDoneRequest", "sync_fraction", 4, F.TYPE_DOUBLE,
     "syncFraction"),
    ("ReportBatchDoneRequest", "push_staleness", 5, F.TYPE_DOUBLE,
     "pushStaleness"),
    ("ReportBatchDoneRequest", "window_size", 6, F.TYPE_DOUBLE,
     "windowSize"),
    ("ReportBatchDoneRequest", "steps_done", 7, F.TYPE_INT64,
     "stepsDone"),
    # Multi-tenant scheduler (docs/scheduler.md): J jobs share one
    # worker pool, so every control-plane RPC that used to be
    # implicitly "the job" becomes job-scoped.  Tasks are stamped with
    # their owning job (task ids are only unique per job), workers
    # echo the job on results/progress so a report landing after a
    # re-assignment still routes to the job it belongs to, and the
    # get_task response carries the assignment (+ the job's worker
    # config as the re-assignment handshake payload).  0 = single-job
    # master, all fields ignored.
    ("TaskPB", "job_id", 5, F.TYPE_INT32, "jobId"),
    ("GetTaskRequest", "job_id", 3, F.TYPE_INT32, "jobId"),
    ("GetTaskResponse", "job_id", 2, F.TYPE_INT32, "jobId"),
    ("GetTaskResponse", "job_config", 3, F.TYPE_STRING, "jobConfig"),
    ("ReportTaskResultRequest", "job_id", 5, F.TYPE_INT32, "jobId"),
    ("ReportBatchDoneRequest", "job_id", 8, F.TYPE_INT32, "jobId"),
    ("GetCommRankRequest", "job_id", 2, F.TYPE_INT32, "jobId"),
    ("ReportTrainLoopStatusRequest", "job_id", 3, F.TYPE_INT32,
     "jobId"),
    ("ReportVersionRequest", "job_id", 6, F.TYPE_INT32, "jobId"),
    ("ReportEvaluationMetricsRequest", "job_id", 5, F.TYPE_INT32,
     "jobId"),
    # Percentile-grade telemetry (docs/observability.md): a compact
    # sparse histogram delta (utils/hist.py encode_deltas — fixed
    # shared bucket bounds, so the master's merge is exact) rides the
    # progress report the worker already sends every fused window.
    # Today it carries the per-step step-time distribution; the
    # master's per-job p50/p99 step time and the straggler detector
    # both derive from it.
    ("ReportBatchDoneRequest", "hist_delta", 9, F.TYPE_STRING,
     "histDelta"),
    # Frame-wire negotiation (docs/ps_pipeline.md "Frame wire"): a PS
    # shard advertises the raw-frame data plane on every legacy dense
    # pull response; a capable client upgrades that shard's push/pull
    # traffic to the push_gradients_frame / pull_dense_parameters_frame
    # methods (one zero-copy frame blob per RPC instead of repeated
    # TensorPB), falling back per shard on UNIMPLEMENTED.
    ("PullDenseParametersResponse", "frame_capable", 5, F.TYPE_BOOL,
     "frameCapable"),
]


def _load_current_blob():
    with open(PB2_PATH, "r") as f:
        src = f.read()
    m = re.search(r"AddSerializedFile\((b'.*?')\)", src, re.S)
    if not m:
        raise SystemExit("cannot find serialized descriptor in %s" % PB2_PATH)
    return eval(m.group(1))  # noqa: S307 — our own generated literal


def _apply_edits(fdp):
    changed = False
    by_name = {m.name: m for m in fdp.message_type}
    for msg_name, field_name, number, ftype, json_name in EDITS:
        msg = by_name[msg_name]
        if any(f.name == field_name for f in msg.field):
            continue
        field = msg.field.add()
        field.name = field_name
        field.number = number
        field.label = F.LABEL_OPTIONAL
        field.type = ftype
        field.json_name = json_name
        changed = True
    return changed


def _walk_messages(prefix, messages):
    """Yield (VARNAME, DescriptorProto) in protoc emission order."""
    for msg in messages:
        var = prefix + "_" + msg.name.upper() if prefix else (
            "_" + msg.name.upper()
        )
        yield var, msg
        yield from _walk_messages(var, msg.nested_type)


def _offsets_block(fdp, blob):
    """The `if _USE_C_DESCRIPTORS == False:` section: map-entry options
    plus serialized start/end offsets found by substring search (the
    serializer that produced `blob` also serializes the sub-descriptors,
    so the bytes match)."""
    lines = ["  DESCRIPTOR._options = None"]
    entries = []
    for var, msg in _walk_messages("", fdp.message_type):
        if msg.options.map_entry:
            lines.append("  %s._options = None" % var)
            lines.append(
                "  %s._serialized_options = b'8\\001'" % var
            )
        entries.append((var, msg.SerializeToString()))
    for enum in fdp.enum_type:
        entries.append(("_" + enum.name.upper(), enum.SerializeToString()))
    for var, sub in entries:
        start = blob.find(sub)
        if start < 0:
            raise SystemExit("descriptor bytes for %s not found" % var)
        lines.append("  %s._serialized_start=%d" % (var, start))
        lines.append("  %s._serialized_end=%d" % (var, start + len(sub)))
    return "\n".join(lines)


TEMPLATE = '''\
# -*- coding: utf-8 -*-
# Generated by scripts/gen_proto.py (no protoc in the image).  DO NOT
# EDIT BY HAND — change scripts/gen_proto.py EDITS + proto/elastic.proto
# and rerun the script.
# source: elastic.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'elastic_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

{offsets}
# @@protoc_insertion_point(module_scope)
'''


def main(argv):
    check = "--check" in argv
    blob = _load_current_blob()
    fdp = descriptor_pb2.FileDescriptorProto.FromString(blob)
    _apply_edits(fdp)
    new_blob = fdp.SerializeToString()
    out = TEMPLATE.format(
        blob=new_blob, offsets=_offsets_block(fdp, new_blob)
    )
    with open(PB2_PATH, "r") as f:
        current = f.read()
    if current == out:
        print("elastic_pb2.py up to date")
        return 0
    if check:
        print("elastic_pb2.py is stale; run scripts/gen_proto.py")
        return 1
    with open(PB2_PATH, "w") as f:
        f.write(out)
    print("rewrote %s (%d descriptor bytes)" % (PB2_PATH, len(new_blob)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
