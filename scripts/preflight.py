"""Green-HEAD gate: refuse to snapshot a broken tree (VERDICT r3 #4).

Runs, in order, each in a fresh subprocess with the CPU platform pinned:

  1. elastic-lint + compileall (scripts/lint.sh — static analysis of
     the elastic control plane: per-file EL001-EL004/EL007 plus the
     whole-program EL005 lock-order / EL006 blocking-under-lock /
     EL008 RPC-conformance pass; emits the EL005 lock-order graph to
     artifacts/lock_graph.dot)
  2. the Prometheus exposition-format conformance tests (every
     /metrics renderer vs the strict parser + metric registry)
  3. the full test suite (pytest tests -q)
  4. the driver's multi-chip dry run (__graft_entry__.dryrun_multichip(8))
  5. one bench.py pass (CPU; validates the JSON contract end-to-end)
  6. bench_tracing.py with BOTH overhead gates (tracing <= 2%,
     histogram path <= 2% steps/s)
  7. bench_serving.py --wire: the binary serving data plane's gates
     (e2e ratio within 25% of the endpoint-layer ratio, binary p99
     within 10% of JSON's, JSON-vs-binary bit-identity, router
     byte-identical pass-through)
  8. bench_ps_wire.py --frame_only: the frame-native PS data plane's
     gates (decode-copy bytes >= 1.3x smaller than TensorPB at equal
     wire dtype, loopback steps/s >= 1.0x, same-seed serialized
     losses bit-identical frame-vs-pb)

Exits nonzero on the FIRST failure with the failing stage named.  Run it
before every end-of-round snapshot — round 2 shipped a broken HEAD
because nothing enforced this mechanically (reference analog: the CI job
gate, scripts/validate_job_status.py + scripts/travis/run_job.sh:1-30).

Usage: python scripts/preflight.py [--fast]
  --fast skips the bench pass (suite + dryrun only, ~12 min -> ~10 min).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CPU_ENV = {
    "ELASTICDL_TPU_PLATFORM": "cpu",
    "JAX_PLATFORMS": "cpu",
}


def run_stage(name, argv, extra_env=None, timeout=2400):
    print("[preflight] %s ..." % name, flush=True)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            argv, cwd=REPO, timeout=timeout,
            env={**os.environ, **CPU_ENV, **(extra_env or {})},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
    except subprocess.TimeoutExpired:
        print("[preflight] FAIL %s: timed out after %ds" % (name, timeout))
        return False, ""
    secs = time.monotonic() - t0
    if proc.returncode != 0:
        print(proc.stdout[-4000:])
        print("[preflight] FAIL %s: exit %d after %.0fs"
              % (name, proc.returncode, secs))
        return False, proc.stdout
    print("[preflight] ok %s (%.0fs)" % (name, secs), flush=True)
    return True, proc.stdout


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    fast = "--fast" in argv

    # Cheapest gate first: static analysis + compile sweep (~seconds)
    # catches control-plane lock/servicer/thread regressions before
    # the 10-minute suite spends any time.
    ok, _ = run_stage(
        "elastic-lint",
        ["bash", os.path.join(REPO, "scripts", "lint.sh")],
        timeout=300,
    )
    if not ok:
        return 1

    # Exposition-format conformance next (seconds): every /metrics
    # renderer against the strict parser + the metric registry —
    # a malformed scrape or an undeclared series fails before the
    # full suite spends any time.
    ok, _ = run_stage(
        "prom-exposition",
        [sys.executable, "-m", "pytest",
         "tests/test_prom_exposition.py", "-q"],
        timeout=300,
    )
    if not ok:
        return 1

    ok, _ = run_stage(
        "pytest", [sys.executable, "-m", "pytest", "tests", "-q"],
        extra_env={
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"
        },
    )
    if not ok:
        return 1

    ok, _ = run_stage(
        "dryrun_multichip(8)",
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        extra_env={
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"
        },
        timeout=900,
    )
    if not ok:
        return 1

    if not fast:
        ok, out = run_stage(
            "bench.py (cpu)", [sys.executable, "bench.py"],
            extra_env={"ELASTICDL_BENCH_TOTAL_BUDGET": "580"},
            timeout=700,
        )
        if not ok:
            return 1
        sys.path.insert(0, REPO)
        from elasticdl_tpu.utils.jsonline import last_json_line

        parsed = last_json_line(out)
        if not parsed or parsed.get("value") is None:
            print("[preflight] FAIL bench.py: no usable JSON value "
                  "(tail=%r)" % out.strip().splitlines()[-3:])
            return 1
        print("[preflight] bench value: %s %s"
              % (parsed["value"], parsed["unit"]))

        # Observability-plane overhead gates (ISSUE 14): tracing AND
        # histogram-path legs must both sit within the 2% steps/s
        # budget.
        ok, out = run_stage(
            "bench_tracing.py (overhead gates)",
            [sys.executable, "bench_tracing.py"],
            timeout=900,
        )
        if not ok:
            return 1
        parsed = last_json_line(out)
        detail = (parsed or {}).get("detail", {})
        if not detail.get("within_2pct"):
            print("[preflight] FAIL bench_tracing: tracing leg over "
                  "the 2%% gate (ratio %s)" % (parsed or {}).get(
                      "value"))
            return 1
        hist_leg = detail.get("histogram_path", {})
        if not hist_leg.get("within_2pct"):
            print("[preflight] FAIL bench_tracing: histogram leg "
                  "over the 2%% gate (ratio %s)"
                  % hist_leg.get("steps_ratio"))
            return 1
        print("[preflight] overhead ratios: tracing %s, histogram %s"
              % (parsed["value"], hist_leg.get("steps_ratio")))

        # Binary serving data plane (ISSUE 15): the e2e-approaches-
        # endpoint ratio gate, the serving.request p99 gate, JSON-vs-
        # binary bit-identity, and router byte-identical pass-through
        # — bench_serving.py --wire exits nonzero itself when any gate
        # fails; the detail check below keeps the verdict visible.
        ok, out = run_stage(
            "bench_serving.py --wire (binary-plane gates)",
            [sys.executable, "bench_serving.py", "--wire",
             "--requests_per_client", "30", "--blocks", "4"],
            timeout=900,
        )
        if not ok:
            return 1
        parsed = last_json_line(out)
        detail = (parsed or {}).get("detail", {})
        if not detail.get("all_green"):
            print("[preflight] FAIL bench_serving --wire: gates %s"
                  % detail.get("gates"))
            return 1
        print("[preflight] binary plane: e2e/endpoint %s (json %s), "
              "p99 %s vs %s ms"
              % (parsed.get("value"), parsed.get("vs_baseline"),
                 detail.get("p99_ms_binary_server_side"),
                 detail.get("p99_ms_json_server_side")))

        # Frame-native PS data plane (ISSUE 17): frame-vs-TensorPB at
        # equal wire dtype — decode-copy bytes >= 1.3x smaller,
        # loopback steps/s >= 1.0x, and same-seed serialized losses
        # bit-identical.  bench_ps_wire --frame_only exits nonzero
        # itself when any gate fails.
        ok, out = run_stage(
            "bench_ps_wire.py --frame_only (frame-wire gates)",
            [sys.executable, "bench_ps_wire.py", "--frame_only"],
            timeout=900,
        )
        if not ok:
            return 1
        parsed = last_json_line(out)
        gates = (parsed or {}).get("gates", {})
        if not (parsed or {}).get("pass"):
            print("[preflight] FAIL bench_ps_wire --frame_only: "
                  "gates %s" % gates)
            return 1
        detail = (parsed or {}).get("detail", {})
        print("[preflight] frame wire: decode-copy %sx, loopback "
              "steps %sx, bit-identical %s"
              % (parsed.get("value"),
                 detail.get("steps_ratio_frame_over_pb_loopback"),
                 gates.get("losses_bit_identical")))

    print("[preflight] ALL GREEN")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
