#!/bin/bash
# Tail-of-session watcher: stop probing by 21:10 UTC so nothing
# contends with the driver's end-of-round bench run; on a healthy
# probe, fire the full bench window.
LOG=/root/repo/.relay_probe.log
cd /root/repo
while [ "$(date -u +%H%M)" -lt 2110 ]; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 150 python -c "
import jax
d = jax.devices()
import jax.numpy as jnp
x = jnp.ones((128,128)); v = float((x@x).sum())
print('PROBE-OK', d[0].platform, v, flush=True)
" 2>&1 | grep "PROBE-OK" | head -1)
  echo "$ts tailprobe out=[$out]" >> "$LOG"
  if [ -n "$out" ]; then
    echo "RELAY HEALTHY at $ts: $out" >> "$LOG"
    bash scripts/tpu_window.sh >> "$LOG" 2>&1
    exit 0
  fi
  sleep 120
done
