"""Kernel A/B matrix on the real chip (VERDICT r3 #2/#5).

Runs the promoted-kernel candidates as watchdog'd subprocesses, each
with its own timeout and the shared persistent compilation cache, and
prints one JSON line with every measured row.  Configs:

ResNet-50 (bench.py --inner, batch 128, img/s):
  baseline      XLA GroupNorm, 7x7 stem
  fusedgn       Pallas fused GroupNorm(+ReLU)
  s2d           space-to-depth stem (4x4/1 conv on C=12)
  s2d+fusedgn   both

Flagship LM (bench_transformer.py, 436M params, tok/s):
  default           Pallas flash fwd+bwd, full per-layer remat
  xla_bwd           flash fwd + XLA block-recompute bwd
  remat_attn        Pallas flash fwd+bwd, remat="attn" (no flash
                    recompute in the backward)
  chunked_xent      no-[B,T,V]-logits loss (T-chunked ln_f+head+xent)
  attn+chunked      remat="attn" + chunked loss
  attn+chunked_b16  same at batch 16 (memory freed by the above)

Decode (bench_transformer.py --decode, generated tok/s):
  decode_mha        KV-cache decode, full head count
  decode_gqa4       grouped-query attention, 4 KV heads (4x smaller
                    cache on the HBM-bound decode path)

Use: run with a healthy relay; results go to BENCHMARKS.md and winners
become defaults.  A wedged relay costs one failed probe (<=90 s), not
the whole matrix.
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))

RESNET_CONFIGS = [
    ("baseline", {"ELASTICDL_FUSED_GN": "off"}),
    ("fusedgn", {"ELASTICDL_FUSED_GN": "tpu"}),
    ("s2d", {"ELASTICDL_FUSED_GN": "off", "ELASTICDL_RESNET_S2D": "1"}),
    ("s2d+fusedgn",
     {"ELASTICDL_FUSED_GN": "tpu", "ELASTICDL_RESNET_S2D": "1"}),
]

DECODE_CONFIGS = [
    ("decode_mha", {}),
    ("decode_gqa4", {"ELASTICDL_BENCH_KV_HEADS": "4"}),
]

LM_CONFIGS = [
    ("default", {}),
    ("xla_bwd", {"ELASTICDL_FLASH_BWD": "xla"}),
    ("remat_attn", {"ELASTICDL_BENCH_REMAT": "attn"}),
    ("chunked_xent", {"ELASTICDL_BENCH_CHUNKED_XENT": "512"}),
    ("attn+chunked", {"ELASTICDL_BENCH_REMAT": "attn",
                      "ELASTICDL_BENCH_CHUNKED_XENT": "512"}),
    ("attn+chunked_b16", {"ELASTICDL_BENCH_REMAT": "attn",
                          "ELASTICDL_BENCH_CHUNKED_XENT": "512",
                          "ELASTICDL_BENCH_BATCH": "16"}),
]


def _run(argv, env, timeout):
    """Returns (parsed_json|None, reason, returncode|None)."""
    from elasticdl_tpu.utils.jsonline import last_json_line

    try:
        proc = subprocess.run(
            [sys.executable] + argv, capture_output=True, text=True,
            timeout=timeout, env={**os.environ, **env}, cwd=HERE,
        )
    except subprocess.TimeoutExpired as e:
        stderr = e.stderr
        if isinstance(stderr, bytes):
            stderr = stderr.decode("utf-8", "replace")
        marks = [ln for ln in (stderr or "").splitlines()
                 if ln.startswith("BENCHMARK-MARK ")]
        return None, "timeout %ds at %s" % (
            timeout, marks[-1].split(" ", 1)[1] if marks else "?"), None
    result = last_json_line(proc.stdout)
    if result is not None:
        return result, "", proc.returncode
    return None, "no JSON (exit %d); stderr: %s" % (
        proc.returncode, (proc.stderr or "")[-200:]), proc.returncode


def main():
    per_cfg = int(os.environ.get("ELASTICDL_AB_TIMEOUT", "420"))
    rows = {"resnet": {}, "lm": {}}

    _, reason, rc = _run(["bench.py", "--probe"], {}, 90)
    # --probe prints PROBE-OK (not JSON) and exits 0 iff the relay
    # answered — the exit status is the health signal.
    if rc != 0:
        print(json.dumps({"error": "relay probe failed: %s" % reason}))
        return 1

    for name, env in RESNET_CONFIGS:
        t0 = time.monotonic()
        res, reason, _rc = _run(
            ["bench.py", "--inner", "--batch", "128"], env, per_cfg)
        rows["resnet"][name] = (
            {"img_per_sec": res["value"],
             "ms_per_step": res["detail"]["ms_per_step"],
             "mfu": res["detail"]["mfu_estimate"],
             "compile_secs": res["detail"]["compile_secs"],
             "samples": res["detail"].get("samples")}
            if res else {"error": reason}
        )
        if res and "device" not in rows:
            rows["device"] = res["detail"].get("device")
        print("resnet/%s: %s (%.0fs)" % (
            name, rows["resnet"][name], time.monotonic() - t0),
            file=sys.stderr, flush=True)

    for name, env in LM_CONFIGS:
        t0 = time.monotonic()
        res, reason, _rc = _run(["bench_transformer.py"], env, per_cfg)
        rows["lm"][name] = (
            {"tok_per_sec": res["value"],
             "ms_per_step": res["detail"]["ms_per_step"],
             "mfu": res["detail"]["mfu_estimate"],
             "compile_secs": res["detail"]["compile_secs"],
             "samples": res["detail"].get("samples")}
            if res else {"error": reason}
        )
        if res and "device" not in rows:
            rows["device"] = res["detail"].get("device")
        print("lm/%s: %s (%.0fs)" % (
            name, rows["lm"][name], time.monotonic() - t0),
            file=sys.stderr, flush=True)

    rows["decode"] = {}
    for name, env in DECODE_CONFIGS:
        t0 = time.monotonic()
        res, reason, _rc = _run(
            ["bench_transformer.py", "--decode"], env, per_cfg)
        rows["decode"][name] = (
            {"tok_per_sec": res["value"],
             "ms_per_token_batch": res["detail"]["ms_per_token_batch"],
             "kv_heads": res["detail"]["kv_heads"],
             "compile_secs": res["detail"]["compile_secs"],
             "samples": res["detail"].get("samples")}
            if res else {"error": reason}
        )
        if res and "device" not in rows:
            rows["device"] = res["detail"].get("device")
        print("decode/%s: %s (%.0fs)" % (
            name, rows["decode"][name], time.monotonic() - t0),
            file=sys.stderr, flush=True)

    print(json.dumps({"metric": "kernel_ab_matrix", "rows": rows}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
