"""Data-plane throughput: the IO side of the training path.

bench.py trains on device-resident synthetic batches and promises "the
data plane is benchmarked separately" — this is that benchmark.  It
measures records/sec and MB/sec through the real reader stack
(data/recio.py, data/reader.py, data/parallel_reader.py) on records
sized like the headline workloads:

  recio_seq       sequential recio shard read (raw payload path)
  recio_shuffled  random-access read honoring a permutation
                  (the master's shuffle contract, O(1) seeks)
  csv             TextDataReader line reads via its byte-offset index
  recio_parallel  ParallelShardReader over a 4-process spawn pool
                  (NOTE: this image pins everything to one core, so the
                  pool measures dispatch overhead, not speedup — on
                  multi-core hosts the same path scales by process)

Reference anchor: the data layer the reference benchmarks through its
RecordIO reader + ODPS multiprocess reader
(elasticdl/python/data/reader/recordio_reader.py:27-63, odps_io).

Prints exactly one JSON line.
"""

import json
import os
import sys
import tempfile
import time

RECORD_BYTES = 1024       # ~CIFAR/CTR example scale
NUM_RECORDS = 50_000


def _build_dataset(root):
    from elasticdl_tpu.data.recio import RecioWriter

    payload = os.urandom(RECORD_BYTES)
    recio_path = os.path.join(root, "shard-0.rec")
    with RecioWriter(recio_path) as w:
        for _ in range(NUM_RECORDS):
            w.write(payload)
    csv_path = os.path.join(root, "data.csv")
    line = ",".join(["0.123456"] * 16) + ",1\n"
    with open(csv_path, "w") as f:
        f.write(line * NUM_RECORDS)
    return recio_path, csv_path


def _rate(fn, n_records, bytes_per_record):
    t0 = time.perf_counter()
    count = fn()
    secs = time.perf_counter() - t0
    assert count == n_records, (count, n_records)
    return {
        "records_per_sec": round(count / secs, 1),
        "mb_per_sec": round(count * bytes_per_record / secs / 2**20, 1),
        "secs": round(secs, 3),
    }


def run_bench():
    import numpy as np

    from elasticdl_tpu.data.parallel_reader import (
        ParallelShardReader,
        _make_task,
    )
    from elasticdl_tpu.data.reader import RecioDataReader, TextDataReader

    rows = {}
    with tempfile.TemporaryDirectory(prefix="edl_bench_data_") as root:
        recio_path, csv_path = _build_dataset(root)

        reader = RecioDataReader(root)
        task = _make_task(recio_path, 0, NUM_RECORDS)
        reader._reader(recio_path)  # build the offset index untimed
        rows["recio_seq"] = _rate(
            lambda: sum(1 for _ in reader.read_records(task)),
            NUM_RECORDS, RECORD_BYTES,
        )

        perm = np.random.RandomState(0).permutation(NUM_RECORDS)
        shuffled = _make_task(
            recio_path, 0, NUM_RECORDS, record_indices=perm.tolist()
        )
        rows["recio_shuffled"] = _rate(
            lambda: sum(1 for _ in reader.read_records(shuffled)),
            NUM_RECORDS, RECORD_BYTES,
        )

        csv_reader = TextDataReader(csv_path, records_per_task=NUM_RECORDS)
        csv_task = _make_task(csv_path, 0, NUM_RECORDS)
        csv_bytes = os.path.getsize(csv_path) / NUM_RECORDS
        rows["csv"] = _rate(
            lambda: sum(1 for _ in csv_reader.read_records(csv_task)),
            NUM_RECORDS, csv_bytes,
        )

        import functools

        with ParallelShardReader(
            functools.partial(RecioDataReader, root),
            num_processes=4, records_per_subrange=2048,
        ) as preader:
            # warm the pool: spawn startup + per-process index scans
            # must not pollute the steady-state measurement
            sum(1 for _ in preader.read_records(task))
            rows["recio_parallel"] = _rate(
                lambda: sum(1 for _ in preader.read_records(task)),
                NUM_RECORDS, RECORD_BYTES,
            )

    return {
        "metric": "data_plane_read_throughput",
        "value": rows["recio_seq"]["records_per_sec"],
        "unit": "records/sec (recio sequential)",
        "vs_baseline": None,
        "detail": {
            "record_bytes": RECORD_BYTES,
            "num_records": NUM_RECORDS,
            "nproc": os.cpu_count(),
            **rows,
            "baseline": "reference publishes no reader throughput; "
                        "this is the framework's own anchor",
        },
    }


if __name__ == "__main__":
    print(json.dumps(run_bench()))
    sys.exit(0)
