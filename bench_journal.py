"""Journal overhead benchmark: steps/s with the job-state journal on
vs off (master/journal.py), at the default report cadence.

What the journal can slow down is the CONTROL PLANE: every worker-side
step ends in a report RPC (`report_batch_done` per minibatch at the
default ``--fused_steps 1`` cadence, `report_task_result` per task),
and the journal's durable flushes ride exactly those handlers.  The
device step itself never touches the journal, so the honest
ACCEPTANCE measurement is end-to-end worker steps/s — a real
``CollectiveTrainer.train_minibatch`` per report, driving a real gRPC
master at the default cadence, journal on vs off.  A zero-compute
report-path hammer is also reported as the worst-case bound (pure
control-plane rate with no training between reports — no real worker
runs there, but it's the number that bounds any cadence).

Harness matches bench_zero.py: interleaved timed blocks with per-pair
leg-order alternation (machine-load drift lands on both legs equally),
gate = MEDIAN of per-block on/off steps/s ratios, acceptance "within
noise" at +/-5%.  Prints exactly one JSON line.
"""

import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

BATCH_SIZE = 32
MINIBATCHES_PER_TASK = 8          # default --num_minibatches_per_task
TASKS_PER_BLOCK = 16              # 128 real train steps per block
HAMMER_TASKS_PER_BLOCK = 48       # zero-compute blocks are fast
BLOCK_PAIRS = 5


def _master(with_journal, tasks):
    """A fresh master over real gRPC; returns (client, finish)."""
    from elasticdl_tpu.master.journal import JournalWriter
    from elasticdl_tpu.master.servicer import (
        MasterServicer,
        create_master_service,
    )
    from elasticdl_tpu.master.task_manager import TaskManager
    from elasticdl_tpu.utils import grpc_utils
    from elasticdl_tpu.worker.master_client import MasterClient

    records_per_task = BATCH_SIZE * MINIBATCHES_PER_TASK
    tm = TaskManager(
        training_shards=[("f", 0, tasks * records_per_task)],
        records_per_task=records_per_task,
    )
    jdir = None
    journal = None
    if with_journal:
        jdir = tempfile.mkdtemp(prefix="edl_bench_journal_")
        journal = JournalWriter(jdir)
        tm.attach_journal(journal, bootstrap=True)
    servicer = MasterServicer(tm, journal=journal)
    server, port = create_master_service(servicer)
    channel = grpc_utils.build_channel("localhost:%d" % port)
    grpc_utils.wait_for_channel_ready(channel)
    mc = MasterClient(channel, worker_id=0)

    def finish():
        server.stop(grace=0)
        channel.close()
        extras = {}
        if jdir is not None:
            journal.close()
            extras["journal_bytes"] = os.path.getsize(
                os.path.join(jdir, "job.journal")
            )
            shutil.rmtree(jdir, ignore_errors=True)
        assert tm.finished(), "block did not drain its task queue"
        return extras

    return mc, finish


def run_train_block(with_journal, trainer, data):
    """ACCEPTANCE leg: real train steps between reports at the default
    cadence.  Returns (steps_per_sec, extras).

    steps/s is MINIBATCHES_PER_TASK / MEDIAN per-task wall time over
    the block.  Per-task, not block-total: on this 2-core CI box
    scheduler/GC spikes hit a few tasks hard, and a block-total mean
    charges a whole spike to whichever leg caught it — the per-task
    median discards it from both legs symmetrically.  Per-task, not
    per-step: the journal's durable flush rides `report_task_result`
    (one per task), so a task is the smallest unit that contains the
    full cadence cost."""
    mc, finish = _master(with_journal, TASKS_PER_BLOCK)
    task_secs = []
    steps = 0
    while True:
        t0 = time.perf_counter()
        task = mc.get_task()
        if task.id < 0:
            break
        for _ in range(MINIBATCHES_PER_TASK):
            loss, _ = trainer.train_minibatch(*data[steps % len(data)])
            float(loss)  # fence: the step's value, not just dispatch
            mc.report_batch_done(BATCH_SIZE)
            steps += 1
        mc.report_task_result(task.id)
        task_secs.append(time.perf_counter() - t0)
    extras = finish()
    return MINIBATCHES_PER_TASK / _median(task_secs), extras


def run_hammer_block(with_journal):
    """Worst-case bound: the report path with NO compute between
    reports.  Returns (reports_per_sec, extras); per-task median,
    same rationale as run_train_block (reports per task = the 8 batch
    reports + the task report that carries the durable flush)."""
    mc, finish = _master(with_journal, HAMMER_TASKS_PER_BLOCK)
    task_secs = []
    while True:
        t0 = time.perf_counter()
        task = mc.get_task()
        if task.id < 0:
            break
        for _ in range(MINIBATCHES_PER_TASK):
            mc.report_batch_done(BATCH_SIZE)
        mc.report_task_result(task.id)
        task_secs.append(time.perf_counter() - t0)
    extras = finish()
    return (MINIBATCHES_PER_TASK + 1) / _median(task_secs), extras


def _interleaved_pairs(run, n_pairs):
    """bench_zero idiom: per-pair leg-order alternation so load drift
    lands on both legs equally; one untimed warm pair first."""
    run(True), run(False)
    pairs = []
    for i in range(n_pairs):
        if i % 2 == 0:
            on, extras = run(True)
            off, _ = run(False)
        else:
            off, _ = run(False)
            on, extras = run(True)
        pairs.append((on, off, extras))
    return pairs


def _median(xs):
    xs = sorted(xs)
    mid = len(xs) // 2
    return xs[mid] if len(xs) % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def main():
    t0 = time.monotonic()
    import jax
    import numpy as np
    from jax.sharding import Mesh

    import bench as _bench  # provenance helpers
    from elasticdl_tpu.models import mnist
    from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    spec = mnist.model_spec(learning_rate=1e-3)
    xs, ys = mnist.synthetic_data(n=BATCH_SIZE * 8, seed=0)
    data = [(xs[i * BATCH_SIZE:(i + 1) * BATCH_SIZE],
             ys[i * BATCH_SIZE:(i + 1) * BATCH_SIZE]) for i in range(8)]
    trainer = CollectiveTrainer(
        spec, batch_size=BATCH_SIZE, mesh=mesh, rng_seed=0
    )

    train_pairs = _interleaved_pairs(
        lambda on: run_train_block(on, trainer, data), BLOCK_PAIRS
    )
    hammer_pairs = _interleaved_pairs(run_hammer_block, BLOCK_PAIRS)

    ratio = _median([on / off for on, off, _ in train_pairs])
    on_med = _median([p[0] for p in train_pairs])
    off_med = _median([p[1] for p in train_pairs])
    h_ratio = _median([on / off for on, off, _ in hammer_pairs])
    h_on = _median([p[0] for p in hammer_pairs])
    h_off = _median([p[1] for p in hammer_pairs])
    journal_bytes = next(
        (p[2]["journal_bytes"] for p in train_pairs
         if "journal_bytes" in p[2]), None,
    )

    print(json.dumps({
        "metric": "journal_overhead_steps_ratio",
        "value": round(ratio, 4),
        "unit": "steps/s with journal / without (median of per-block "
                "ratios; 1.0 = free)",
        "vs_baseline": None,
        "detail": {
            "steps_per_sec_journal_on": round(on_med, 1),
            "steps_per_sec_journal_off": round(off_med, 1),
            "within_5pct": 0.95 <= ratio,
            "report_cadence": "one real train_minibatch + one "
                              "report_batch_done per minibatch "
                              "(default --fused_steps 1; fused "
                              "windows coalesce further), one "
                              "report_task_result per task — durable "
                              "fdatasync only on task lifecycle "
                              "events",
            "train_blocks": [
                {"on": round(on, 1), "off": round(off, 1),
                 "ratio": round(on / off, 4)}
                for on, off, _ in train_pairs
            ],
            "report_hammer_worst_case": {
                "note": "zero compute between reports — pure "
                        "control-plane rate; bounds any cadence, no "
                        "real worker runs here",
                "reports_per_sec_journal_on": round(h_on, 1),
                "reports_per_sec_journal_off": round(h_off, 1),
                "ratio": round(h_ratio, 4),
                "added_us_per_report": round(
                    (1e6 / h_on) - (1e6 / h_off), 1
                ),
            },
            "journal_bytes_per_train_block": journal_bytes,
            "tasks_per_train_block": TASKS_PER_BLOCK,
            "env": _bench._env_snapshot(),
            "bench_wall_secs": round(time.monotonic() - t0, 1),
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
