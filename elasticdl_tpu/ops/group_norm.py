"""Fused GroupNorm(+ReLU) Pallas kernel for TPU.

GroupNorm is the normalization of the ResNet family here (models/
resnet.py — BatchNorm needs cross-replica batch-stats sync; GroupNorm
doesn't), and it is HBM-bound: XLA computes stats and normalizes in
separate passes over the activation, and the benchmark ablation showed
it costing ~14.5 ms of the 54.5 ms ResNet-50 step (BENCHMARKS.md).
This kernel does the whole op — stats, normalize, affine, optional
ReLU — in ONE pass over HBM: each grid step holds one batch row
[HW, C] in VMEM, reduces it, and writes the normalized output back.

Backward is a second Pallas kernel (custom VJP): recomputes x-hat from
the saved group stats broadcast per channel (two small [B, 1, C] f32
residuals — the activation itself is never re-saved), applies the
closed-form GroupNorm pullback, and accumulates dscale/dbias across
the sequential TPU grid in a revisited output block.  Inside a grid
step the HW axis is walked in chunks (``_row_chunk``) so the f32
temporaries fit scoped VMEM even for the 112x112 stem map.

Layouts: channels-last [..., C] (the conv layout everywhere in this
framework); stats are over (spatial..., C/G) per group, matching
flax.linen.GroupNorm semantics (models/resnet.py used nn.GroupNorm
before this kernel).  Mode selection mirrors ops/flash_attention.py:
``ELASTICDL_FUSED_GN=auto`` (compiled on TPU, jnp elsewhere),
``interpret`` (for tests), ``off``.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def fused_gn_mode():
    mode = os.environ.get("ELASTICDL_FUSED_GN", "auto")
    if mode == "auto":
        return "tpu" if jax.default_backend() == "tpu" else "off"
    return mode


def _group_norm_ref(x, scale, bias, num_groups, eps, relu):
    """jnp reference (identical math to flax.linen.GroupNorm)."""
    B = x.shape[0]
    C = x.shape[-1]
    xf = x.astype(jnp.float32).reshape(B, -1, num_groups,
                                       C // num_groups)
    mean = xf.mean(axis=(1, 3), keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=(1, 3), keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(x.shape) * scale.astype(jnp.float32) \
        + bias.astype(jnp.float32)
    if relu:
        y = jax.nn.relu(y)
    return y.astype(x.dtype)


# -- forward kernel ---------------------------------------------------------


def _membership(L, num_groups, logical_C):
    """[L, G] one-hot lane->group matrix.  Group reductions become two
    small MXU matmuls ([1,L]@[L,G] then [1,G]@[G,L]) — Mosaic has no
    efficient lowering for the [C]->[G, C/G] reshape (C/G can be 2,
    far below the 128-lane tile), matmuls it always has.

    L may be a lane-folded layout (narrow C folds rows into lanes so
    C=64 doesn't waste half of every 128-lane vector register and
    every DMA): physical lane l holds logical channel l % logical_C.
    """
    gsz = logical_C // num_groups
    chan = jax.lax.broadcasted_iota(jnp.int32, (L, num_groups), 0) \
        % logical_C
    grp = jax.lax.broadcasted_iota(jnp.int32, (L, num_groups), 1)
    return (chan // gsz == grp).astype(jnp.float32)


def _group_mean_c(row, memb, n):
    """row [1, C] -> per-group mean broadcast back to [1, C]."""
    return jnp.dot(
        jnp.dot(row, memb, preferred_element_type=jnp.float32),
        memb.T, preferred_element_type=jnp.float32,
    ) / n


def _row_chunk(HW, C):
    """Rows per in-kernel chunk: cap the f32 temporaries at ~2 MB while
    keeping the chunk count a clean divisor of HW (halving only while
    even), so big feature maps fit scoped VMEM."""
    chunk = HW
    while chunk * C * 4 > 2 * 1024 * 1024 and chunk % 2 == 0:
        chunk //= 2
    return chunk


def _fwd_kernel(x_ref, scale_ref, bias_ref, out_ref, mean_ref, rstd_ref,
                csum_ref, csumsq_ref, *, num_groups, eps, relu, chunk,
                logical_C):
    L = x_ref.shape[-1]
    HW = x_ref.shape[1]
    gsz = logical_C // num_groups
    n = HW * (L // logical_C) * gsz      # logical elements per group
    memb = _membership(L, num_groups, logical_C)

    # Pass 1 over VMEM (chunked so f32 temps stay small): channel sums
    # -> group means.
    csum_ref[...] = jnp.zeros_like(csum_ref)

    def mean_body(i, _):
        xs = x_ref[0, pl.ds(i * chunk, chunk), :].astype(jnp.float32)
        csum_ref[...] += jnp.sum(xs, axis=0, keepdims=True)
        return 0

    jax.lax.fori_loop(0, HW // chunk, mean_body, 0)
    mean_c = _group_mean_c(csum_ref[...], memb, n)       # [1, C]

    # Pass 2: CENTERED second moment.  E[x^2]-E[x]^2 catastrophically
    # cancels in f32 when |mean| >> std (un-normalized inputs); the
    # data is already resident in VMEM, so the extra pass costs no HBM
    # traffic and matches nn.GroupNorm's two-pass variance exactly.
    csumsq_ref[...] = jnp.zeros_like(csumsq_ref)

    def var_body(i, _):
        xs = x_ref[0, pl.ds(i * chunk, chunk), :].astype(jnp.float32)
        d = xs - mean_c
        csumsq_ref[...] += jnp.sum(d * d, axis=0, keepdims=True)
        return 0

    jax.lax.fori_loop(0, HW // chunk, var_body, 0)
    var_c = _group_mean_c(csumsq_ref[...], memb, n)
    rstd_c = jax.lax.rsqrt(var_c + eps)
    mean_ref[0] = mean_c
    rstd_ref[0] = rstd_c
    a = rstd_c * scale_ref[...].astype(jnp.float32)
    b = bias_ref[...].astype(jnp.float32) - mean_c * a

    # Pass 2 over VMEM: normalize + affine (+ ReLU).
    def norm_body(i, _):
        xs = x_ref[0, pl.ds(i * chunk, chunk), :].astype(jnp.float32)
        y = xs * a + b
        if relu:
            y = jnp.maximum(y, 0.0)
        out_ref[0, pl.ds(i * chunk, chunk), :] = y.astype(out_ref.dtype)
        return 0

    jax.lax.fori_loop(0, HW // chunk, norm_body, 0)


def _fold(x3):
    """Fold rows into lanes while C < 128 (keeps every 128-wide vector
    register and DMA fully populated).  Returns (folded, logical_C)."""
    B, HW, C = x3.shape
    while C < 128 and HW % 2 == 0:
        HW //= 2
        C *= 2
    return x3.reshape(B, HW, C), x3.shape[-1]


def _fwd_pallas(x3, scale, bias, num_groups, eps, relu, interpret):
    x3, logical_C = _fold(x3)
    B, HW, C = x3.shape
    r = C // logical_C
    scale = jnp.tile(scale.reshape(1, logical_C), (1, r))
    bias = jnp.tile(bias.reshape(1, logical_C), (1, r))
    out, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, num_groups=num_groups, eps=eps,
                          relu=relu, chunk=_row_chunk(HW, C),
                          logical_C=logical_C),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, HW, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, C), lambda b: (0, 0)),
            pl.BlockSpec((1, C), lambda b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, HW, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 1, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 1, C), lambda b: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, HW, C), x3.dtype),
            jax.ShapeDtypeStruct((B, 1, C), jnp.float32),
            jax.ShapeDtypeStruct((B, 1, C), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, C), jnp.float32),
            pltpu.VMEM((1, C), jnp.float32),
        ],
        interpret=interpret,
    )(x3, scale, bias)
    return out, mean, rstd


# -- backward kernel --------------------------------------------------------


def _bwd_kernel(x_ref, dy_ref, scale_ref, bias_ref, mean_ref, rstd_ref,
                dx_ref, dscale_ref, dbias_ref, s1_ref, s2_ref,
                *, num_groups, eps, relu, chunk, logical_C):
    L = x_ref.shape[-1]
    HW = x_ref.shape[1]
    gsz = logical_C // num_groups
    n = HW * (L // logical_C) * gsz
    memb = _membership(L, num_groups, logical_C)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dscale_ref[...] = jnp.zeros_like(dscale_ref)
        dbias_ref[...] = jnp.zeros_like(dbias_ref)

    scale = scale_ref[...].astype(jnp.float32)   # [1, C]
    bias = bias_ref[...].astype(jnp.float32)
    mean_c = mean_ref[0]                         # [1, C]
    rstd_c = rstd_ref[0]
    # Same a/b association order as the forward so the ReLU mask is
    # bit-identical on boundary elements (y == 0).
    a_c = rstd_c * scale
    b_c = bias - mean_c * a_c

    # Pass 1 (chunked): s1 = sum(dy), s2 = sum(dy * xhat) per channel
    # (dy already ReLU-masked).
    s1_ref[...] = jnp.zeros_like(s1_ref)
    s2_ref[...] = jnp.zeros_like(s2_ref)

    def stats_body(i, _):
        sl = pl.ds(i * chunk, chunk)
        xs = x_ref[0, sl, :].astype(jnp.float32)
        xhat = (xs - mean_c) * rstd_c
        dy = dy_ref[0, sl, :].astype(jnp.float32)
        if relu:
            dy = jnp.where(xs * a_c + b_c > 0.0, dy, 0.0)
        s1_ref[...] += jnp.sum(dy, axis=0, keepdims=True)
        s2_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
        return 0

    jax.lax.fori_loop(0, HW // chunk, stats_body, 0)
    dscale_ref[...] += s2_ref[...]
    dbias_ref[...] += s1_ref[...]
    # GroupNorm pullback: dx = rstd*(g - mean_g(g) - xhat*mean_g(g*xhat))
    # with g = dy*scale; the group means come from the channel sums.
    gsum_c = _group_mean_c(s1_ref[...] * scale, memb, n)     # [1, C]
    gxsum_c = _group_mean_c(s2_ref[...] * scale, memb, n)

    def dx_body(i, _):
        sl = pl.ds(i * chunk, chunk)
        xs = x_ref[0, sl, :].astype(jnp.float32)
        xhat = (xs - mean_c) * rstd_c
        dy = dy_ref[0, sl, :].astype(jnp.float32)
        if relu:
            dy = jnp.where(xs * a_c + b_c > 0.0, dy, 0.0)
        dx = rstd_c * (dy * scale - gsum_c - xhat * gxsum_c)
        dx_ref[0, sl, :] = dx.astype(dx_ref.dtype)
        return 0

    jax.lax.fori_loop(0, HW // chunk, dx_body, 0)


def _bwd_pallas(x3, dy3, scale, bias, mean, rstd, num_groups, eps, relu,
                interpret):
    orig_shape = x3.shape
    x3, logical_C = _fold(x3)
    dy3 = dy3.reshape(x3.shape)
    B, HW, C = x3.shape
    r = C // logical_C
    scale_p = jnp.tile(scale.reshape(1, logical_C), (1, r))
    bias_p = jnp.tile(bias.reshape(1, logical_C), (1, r))
    dx, dscale, dbias = pl.pallas_call(
        functools.partial(_bwd_kernel, num_groups=num_groups, eps=eps,
                          relu=relu, chunk=_row_chunk(HW, C),
                          logical_C=logical_C),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, HW, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, HW, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, C), lambda b: (0, 0)),
            pl.BlockSpec((1, C), lambda b: (0, 0)),
            pl.BlockSpec((1, 1, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 1, C), lambda b: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, HW, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, C), lambda b: (0, 0)),
            pl.BlockSpec((1, C), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, HW, C), x3.dtype),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, C), jnp.float32),
            pltpu.VMEM((1, C), jnp.float32),
        ],
        interpret=interpret,
    )(x3, dy3, scale_p, bias_p, mean, rstd)
    # Un-fold the lane-tiled affine grads back to logical channels.
    dscale = dscale.reshape(r, logical_C).sum(axis=0)
    dbias = dbias.reshape(r, logical_C).sum(axis=0)
    return dx.reshape(orig_shape), dscale, dbias


# -- custom-VJP wrapper -----------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused(x, scale, bias, num_groups, eps, relu, interpret):
    return _fused_fwd(x, scale, bias, num_groups, eps, relu,
                      interpret)[0]


def _fused_fwd(x, scale, bias, num_groups, eps, relu, interpret):
    B, C = x.shape[0], x.shape[-1]
    x3 = x.reshape(B, -1, C)
    y3, mean, rstd = _fwd_pallas(x3, scale, bias, num_groups, eps, relu,
                                 interpret)
    return y3.reshape(x.shape), (x3, scale, bias, mean, rstd, x.shape)


def _fused_bwd(num_groups, eps, relu, interpret, res, dy):
    x3, scale, bias, mean, rstd, xshape = res
    dy3 = dy.reshape(x3.shape)
    dx3, dscale, dbias = _bwd_pallas(
        x3, dy3, scale, bias, mean, rstd, num_groups, eps, relu,
        interpret,
    )
    return (dx3.reshape(xshape), dscale.astype(scale.dtype),
            dbias.astype(bias.dtype))


_fused.defvjp(
    lambda x, scale, bias, num_groups, eps, relu, interpret: _fused_fwd(
        x, scale, bias, num_groups, eps, relu, interpret
    ),
    _fused_bwd,
)


def fused_group_norm(x, scale, bias, num_groups, eps=1e-6, relu=False):
    """GroupNorm + affine (+ ReLU) over the trailing channel axis.

    x: [B, spatial..., C]; scale/bias: [C].  Dispatches to the Pallas
    kernel per ELASTICDL_FUSED_GN, else the jnp reference.
    """
    C = x.shape[-1]
    if C % num_groups:
        raise ValueError(
            "channels %d not divisible by %d groups" % (C, num_groups)
        )
    mode = fused_gn_mode()
    if mode in ("tpu", "interpret"):
        return _fused(x, scale, bias, num_groups, eps, relu,
                      mode == "interpret")
    return _group_norm_ref(x, scale, bias, num_groups, eps, relu)
