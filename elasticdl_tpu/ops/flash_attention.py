"""Pallas flash attention for TPU.

The hot op of the long-context path.  One (batch*head, q-block) program
holds its query tile in VMEM and streams K/V tiles of the same head
through the MXU with the online-softmax accumulation, so the T x T score
matrix never materializes in HBM.

Forward emits the per-row softmax stats (l, m) alongside the output, and
the backward is a Pallas kernel pair: a dq pass (q/dO tiles resident,
K/V streamed) and a dk/dv pass (K/V resident, q/dO streamed), each
rebuilding its probability tiles from the saved stats IN VMEM — unlike
the older XLA ``lax.scan`` block-recompute (kept behind
``ELASTICDL_FLASH_BWD=xla``), the [T, block] p/ds tiles never make an
HBM round-trip between einsums.  Peak memory stays O(T·block), never
the full T x T.

``flash_attention_partial`` exposes the same kernel without the final
normalization, returning (acc, l, m) for one KV block — the building
block ring attention folds across ``ppermute`` hops
(parallel/ring_attention.py).  The ring's *forward* thereby skips the
dense per-shard score matrix; its backward is the hand-written
closed-form pullback ``_partial_stats_bwd`` (scans K blocks,
recomputing each [T, block_k] score tile), so each ring step's bwd is
O(T/sp x block_k) live, never the dense per-shard square.

Layout: [batch, heads, seq, head_dim].  The caller-facing block sizes
are a friendliness contract (seq divisible by them, 128-lane block_k);
the kernel chooses its own internal tiling (up to 512-wide q blocks and
K/V major tiles) to amortize per-grid-step overhead.  `flash_attention`
falls back to the reference implementation for unfriendly shapes.
Mode selection: ``ELASTICDL_FLASH=auto`` (default: compiled kernel on
TPU — validated on the real chip 2026-07-29, see BENCHMARKS.md; jnp
elsewhere), ``interpret`` (Pallas interpret mode, for tests), ``off``.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def flash_mode():
    """"tpu" (compiled), "interpret", or "off" for the current config."""
    mode = os.environ.get("ELASTICDL_FLASH", "auto")
    if mode == "auto":
        return "tpu" if jax.default_backend() == "tpu" else "off"
    return mode


def _attention_ref(q, k, v, causal, scale, window=0):
    """jnp reference in the same [B, H, T, D] layout.  ``window`` > 0
    limits causal attention to the last ``window`` positions."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        diff = jnp.arange(tq)[:, None] - jnp.arange(tk)[None, :]
        mask = diff >= 0
        if window:
            mask &= diff < window
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


STATS_LANES = 128  # Mosaic wants >=(8,128) tiles; stats ride 128 lanes
                   # broadcast, same layout as the in-tree TPU kernel.


def _lanes_bcast(x, head_dim):
    """[bq, 128] all-equal-lane stats -> [bq, head_dim]."""
    if head_dim == STATS_LANES:
        return x
    if head_dim < STATS_LANES:
        return x[:, :head_dim]
    return pltpu.repeat(x, head_dim // STATS_LANES, axis=1)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, m_ref, acc_scr,
                  l_scr, m_scr, *, block_k, causal, scale, normalize,
                  window=0):
    # grid: (bh, num_q_blocks, num_k_blocks), K innermost.  Each grid
    # step sees ONE [1, block_k, D] K/V tile — Pallas's automatic
    # pipelining streams tiles HBM->VMEM overlapped with compute, so
    # VMEM never holds the full sequence (the fori_loop-over-resident-KV
    # variant OOMs scoped vmem at T=8k).  The running (acc, l, m) lives
    # in VMEM scratch, persistent across the K grid dimension.
    # Stats stay 2D [block_q, STATS_LANES] (every lane equal) so all
    # vector ops live on full (8, 128) tiles — Mosaic rejects 1D or
    # lane-1 output blocks.  Requires block_k == STATS_LANES so
    # `s - m` stays lane-aligned.
    block_q = q_ref.shape[1]
    block_k_major = k_ref.shape[1]
    head_dim = q_ref.shape[2]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros(acc_scr.shape, acc_scr.dtype)
        l_scr[...] = jnp.zeros(l_scr.shape, l_scr.dtype)
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, m_scr.dtype)

    # Under causal masking, major blocks strictly above the diagonal
    # contribute nothing — skip their matmuls entirely.  A sliding
    # window additionally kills blocks entirely below the band.
    live = (
        ki * block_k_major <= qi * block_q + block_q - 1 if causal
        else ki >= 0
    )
    if causal and window:
        live &= (
            ki * block_k_major + block_k_major - 1
            >= qi * block_q - window + 1
        )

    @pl.when(live)
    def _major_step():
        # Keep the operands in their storage dtype (bf16 in the mixed-
        # precision path) and accumulate in f32 via preferred_element_type
        # — upcasting before the dot would push the MXU onto the ~4x
        # slower f32 path.  The scale folds into the f32 scores.
        q = q_ref[0]                                   # [bq, D]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )

        # One [1, block_k_major, D] K/V tile is streamed per grid step
        # (enough work to amortize the per-step pipeline overhead); the
        # online-softmax update walks it in lane-width chunks.
        @pl.loop(0, block_k_major, step=block_k, unroll=True)
        def _inner(start):
            k = k_ref[0, pl.ds(start, block_k), :]     # [bk, D]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                  # [bq, bk] f32
            if causal:
                k_pos = (
                    ki * block_k_major + start
                    + jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 1
                    )
                )
                keep = q_pos >= k_pos
                if window:
                    keep &= q_pos - k_pos < window
                s = jnp.where(keep, s, NEG_INF)
            m_prev = m_scr[...]
            l_prev = l_scr[...]
            m_new = jnp.maximum(
                m_prev, s.max(axis=-1)[:, None]
            )                                          # [bq, LANES]
            alpha = jnp.exp(m_prev - m_new)            # [bq, LANES]
            p = jnp.exp(s - m_new)         # [bq, bk]; bk == STATS_LANES
            l_scr[...] = l_prev * alpha + p.sum(axis=-1)[:, None]
            m_scr[...] = m_new
            pv = jax.lax.dot_general(
                p.astype(v_ref.dtype),
                v_ref[0, pl.ds(start, block_k), :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_scr[...] = (
                acc_scr[...] * _lanes_bcast(alpha, head_dim) + pv
            )

    @pl.when(ki == num_k - 1)
    def _finish():
        acc = acc_scr[...]
        l = l_scr[...]
        if normalize:
            o_ref[0] = (
                acc / _lanes_bcast(jnp.maximum(l, 1e-30), head_dim)
            ).astype(o_ref.dtype)
        else:
            o_ref[0] = acc.astype(o_ref.dtype)
        l_ref[0] = l
        m_ref[0] = m_scr[...]


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   normalize=True, window=0):
    """Returns (out, l, m); out is normalized iff ``normalize``."""
    b, h, t, d = q.shape
    bh = b * h
    qr = q.reshape(bh, t, d)
    kr = k.reshape(bh, t, d)
    vr = v.reshape(bh, t, d)
    # Work per grid step must amortize the per-step pipeline overhead:
    # widen the q block and stream a major K/V tile (the kernel's inner
    # loop walks it in block_k lane chunks), both capped by what
    # divides t.  The caller's block_q/block_k are a friendliness
    # contract (t divisible, 128 lanes) — the kernel owns its tiling.
    block_q = block_k_major = _major_tile(t)
    grid = (bh, t // block_q, t // block_k_major)
    if causal:
        # Dead blocks above the diagonal skip compute (pl.when in the
        # kernel) — also skip their HBM->VMEM DMA by clamping the K/V
        # index map to the last live block: a revisited block index is
        # deduped by the pipeline into no copy.
        def kv_index(i, j, ki):
            last_live = (j * block_q + block_q - 1) // block_k_major
            if window:
                first_live = jnp.maximum(
                    0, (j * block_q - window + 1) // block_k_major
                )
            else:
                first_live = 0
            return (i, jnp.clip(ki, first_live, last_live), 0)
    else:
        def kv_index(i, j, ki):
            return (i, ki, 0)
    out_dtype = q.dtype if normalize else jnp.float32
    out, l, m = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=block_k, causal=causal, scale=scale,
            normalize=normalize, window=window,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t, d), out_dtype),
            jax.ShapeDtypeStruct((bh, t, STATS_LANES), jnp.float32),
            jax.ShapeDtypeStruct((bh, t, STATS_LANES), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, ki: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k_major, d), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k_major, d), kv_index,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda i, j, ki: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, STATS_LANES),
                         lambda i, j, ki: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, STATS_LANES),
                         lambda i, j, ki: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, STATS_LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr)
    return (
        out.reshape(b, h, t, d),
        l[..., 0].reshape(b, h, t),
        m[..., 0].reshape(b, h, t),
    )


def _kv_blocks(k, v, block_k):
    """Split [B,H,Tk,D] K/V into scan-leading f32 blocks
    [num_k, B, H, block_k, D]."""
    b, h, tk, d = k.shape
    num_k = tk // block_k
    kb = jnp.moveaxis(
        k.reshape(b, h, num_k, block_k, d), 2, 0
    ).astype(jnp.float32)
    vb = jnp.moveaxis(
        v.reshape(b, h, num_k, block_k, d), 2, 0
    ).astype(jnp.float32)
    return num_k, kb, vb


def _masked_block_scores(qf, kf, ki, block_k, causal, scale, k_offset,
                         q_pos, window=0):
    """One [B,H,T,block_k] f32 score tile, causally masked against k
    rows offset by ``k_offset + ki*block_k``.  Returns (scores, mask)
    with mask None when not causal — the single source of truth both
    blockwise backwards recompute from."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", qf, kf,
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        k_pos = k_offset + ki * block_k + jnp.arange(block_k)
        diff = q_pos[:, None] - k_pos[None, :]
        mask = diff >= 0
        if window:
            mask &= diff < window
        mask = mask[None, None]
        return jnp.where(mask, s, NEG_INF), mask
    return s, None


def _blockwise_bwd(q, k, v, out, l, m, g, causal, scale, block_k,
                   window=0):
    """Block-recompute backward: scan over K blocks rebuilding each
    [T, block_k] probability tile from the saved (l, m) stats.  Peak
    live memory O(B·H·T·block_k), never the T x T matrix."""
    _, _, tk, _ = k.shape
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    outf = out.astype(jnp.float32)
    # delta_i = sum_d dO_i O_i  (the usual flash-bwd row constant)
    delta = (gf * outf).sum(axis=-1)                    # [B,H,T]
    l_safe = jnp.maximum(l, 1e-30)
    q_pos = jnp.arange(q.shape[2])

    num_k, k_blocks, v_blocks = _kv_blocks(k, v, block_k)

    def body(carry, inputs):
        dq = carry
        ki, kf, vf = inputs
        s, _ = _masked_block_scores(
            qf, kf, ki, block_k, causal, scale, 0, q_pos, window=window
        )                                               # [B,H,T,bk]
        p = jnp.exp(s - m[..., None]) / l_safe[..., None]
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vf)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq, (dk, dv)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, (dk, dv) = jax.lax.scan(
        body, dq0, (jnp.arange(num_k), k_blocks, v_blocks)
    )
    dk = jnp.moveaxis(dk, 0, 2).reshape(k.shape)
    dv = jnp.moveaxis(dv, 0, 2).reshape(v.shape)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


STATS_OUT = 8  # lanes for stats arrays fed back into the bwd kernels


def _major_tile(t):
    """Shared fwd/bwd major-tile policy: widest of 128/256/512 dividing t
    (enough per-grid-step work to amortize pipeline overhead)."""
    return max(bs for bs in (128, 256, 512) if bs <= t and t % bs == 0)


def _bwd_dq_kernel(q_ref, o_ref, do_ref, k_ref, v_ref, l_ref, m_ref,
                   dq_ref, dq_scr, *, block_k, causal, scale,
                   window=0):
    """dq = sum_j ds_ij k_j.  Grid (bh, NQ, NK), K innermost: the q/o/dO
    tiles and stats stay resident while K/V tiles stream through VMEM;
    the [bq, block_k] probability/ds tiles never exist outside VMEM."""
    block_q = q_ref.shape[1]
    block_k_major = k_ref.shape[1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, dq_scr.dtype)

    live = (
        ki * block_k_major <= qi * block_q + block_q - 1 if causal
        else ki >= 0
    )
    if causal and window:
        live &= (
            ki * block_k_major + block_k_major - 1
            >= qi * block_q - window + 1
        )

    @pl.when(live)
    def _step():
        q = q_ref[0]                                   # [bq, D]
        do = do_ref[0]
        delta = (
            do.astype(jnp.float32) * o_ref[0].astype(jnp.float32)
        ).sum(axis=-1)[:, None]                        # [bq, 1]
        m = m_ref[0][:, 0:1]                           # [bq, 1]
        l = jnp.maximum(l_ref[0][:, 0:1], 1e-30)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )

        @pl.loop(0, block_k_major, step=block_k, unroll=True)
        def _inner(start):
            k = k_ref[0, pl.ds(start, block_k), :]     # [bk, D]
            v = v_ref[0, pl.ds(start, block_k), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                  # [bq, bk]
            if causal:
                k_pos = (
                    ki * block_k_major + start
                    + jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 1
                    )
                )
                keep = q_pos >= k_pos
                if window:
                    keep &= q_pos - k_pos < window
                s = jnp.where(keep, s, NEG_INF)
            p = jnp.exp(s - m) / l                     # normalized
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                          # [bq, bk]
            ds = (p * (dp - delta) * scale).astype(k_ref.dtype)
            dq_scr[...] += jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, o_ref, do_ref, l_ref, m_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, block_q, causal, scale, window=0):
    """dk_j = sum_i ds_ij^T q_i, dv_j = sum_i p_ij^T dO_i.  Grid
    (bh, NK, NQ), Q innermost: the K/V tiles and accumulators stay
    resident while q/o/dO tiles (and their stats) stream through."""
    block_k_major = k_ref.shape[1]
    block_q_major = q_ref.shape[1]
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, dk_scr.dtype)
        dv_scr[...] = jnp.zeros(dv_scr.shape, dv_scr.dtype)

    live = (
        qi * block_q_major + block_q_major - 1 >= kj * block_k_major
        if causal else qi >= 0
    )
    if causal and window:
        live &= (
            qi * block_q_major
            <= kj * block_k_major + block_k_major - 1 + window - 1
        )

    @pl.when(live)
    def _step():
        k = k_ref[0]                                   # [bkM, D]
        v = v_ref[0]
        if causal:
            k_pos = kj * block_k_major + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k_major), 1
            )

        @pl.loop(0, block_q_major, step=block_q, unroll=True)
        def _inner(start):
            q = q_ref[0, pl.ds(start, block_q), :]     # [qc, D]
            o = o_ref[0, pl.ds(start, block_q), :]
            do = do_ref[0, pl.ds(start, block_q), :]
            m = m_ref[0, pl.ds(start, block_q), :][:, 0:1]
            l = jnp.maximum(
                l_ref[0, pl.ds(start, block_q), :][:, 0:1], 1e-30
            )
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                  # [qc, bkM]
            if causal:
                q_pos = (
                    qi * block_q_major + start
                    + jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k_major), 0
                    )
                )
                keep = q_pos >= k_pos
                if window:
                    keep &= q_pos - k_pos < window
                s = jnp.where(keep, s, NEG_INF)
            p = jnp.exp(s - m) / l                     # [qc, bkM]
            pb = p.astype(do_ref.dtype)
            dv_scr[...] += jax.lax.dot_general(
                pb, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                          # [bkM, D]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                          # [qc, bkM]
            delta = (
                do.astype(jnp.float32) * o.astype(jnp.float32)
            ).sum(axis=-1)[:, None]
            ds = (p * (dp - delta) * scale).astype(q_ref.dtype)
            dk_scr[...] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                          # [bkM, D]

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _pallas_bwd(q, k, v, out, l, m, g, causal, scale, interpret,
                window=0):
    """Pallas backward: dq in one pass (K streamed), dk/dv in another
    (Q streamed).  Same FLOPs as the XLA block-recompute path but the
    probability/ds tiles live only in VMEM — no [B,H,T,block] HBM
    round-trips between the einsums of a scan step."""
    b, h, t, d = q.shape
    bh = b * h
    tile = _major_tile(t)
    num = t // tile
    qr = q.reshape(bh, t, d)
    kr = k.reshape(bh, t, d)
    vr = v.reshape(bh, t, d)
    orr = out.reshape(bh, t, d)
    gr = g.astype(q.dtype).reshape(bh, t, d)
    l8 = jnp.broadcast_to(
        l.reshape(bh, t, 1), (bh, t, STATS_OUT)
    ).astype(jnp.float32)
    m8 = jnp.broadcast_to(
        m.reshape(bh, t, 1), (bh, t, STATS_OUT)
    ).astype(jnp.float32)

    qo_spec = pl.BlockSpec((1, tile, d), lambda i, j, kk: (i, j, 0),
                           memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((1, tile, STATS_OUT),
                           lambda i, j, kk: (i, j, 0),
                           memory_space=pltpu.VMEM)
    if causal:
        # Dead blocks skip compute; clamp the streamed-side index map so
        # their HBM->VMEM copies dedupe away too.  (Equal fwd tile
        # sizes, so tile index arithmetic is 1:1.)
        win_tiles = (window + tile - 2) // tile if window else 0

        def kv_index(i, j, kk):
            lo = jnp.maximum(0, j - win_tiles) if window else 0
            return (i, jnp.clip(kk, lo, j), 0)

        def q_index(i, j, kk):
            hi = j + win_tiles if window else num - 1
            return (i, jnp.clip(kk, j, hi), 0)
    else:
        def kv_index(i, j, kk):
            return (i, kk, 0)

        def q_index(i, j, kk):
            return (i, kk, 0)
    kv_spec = pl.BlockSpec((1, tile, d), kv_index,
                           memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=128, causal=causal,
                          scale=scale, window=window),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=(bh, num, num),
        in_specs=[qo_spec, qo_spec, qo_spec, kv_spec, kv_spec,
                  st_spec, st_spec],
        out_specs=qo_spec,
        scratch_shapes=[pltpu.VMEM((tile, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, orr, gr, kr, vr, l8, m8)

    kv_res_spec = pl.BlockSpec((1, tile, d), lambda i, j, kk: (i, j, 0),
                               memory_space=pltpu.VMEM)
    qs_spec = pl.BlockSpec((1, tile, d), q_index,
                           memory_space=pltpu.VMEM)
    sts_spec = pl.BlockSpec((1, tile, STATS_OUT), q_index,
                            memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=128, causal=causal,
                          scale=scale, window=window),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ),
        grid=(bh, num, num),
        in_specs=[kv_res_spec, kv_res_spec, qs_spec, qs_spec, qs_spec,
                  sts_spec, sts_spec],
        out_specs=(kv_res_spec, kv_res_spec),
        scratch_shapes=[
            pltpu.VMEM((tile, d), jnp.float32),
            pltpu.VMEM((tile, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(kr, vr, qr, orr, gr, l8, m8)
    return (
        dq.reshape(b, h, t, d),
        dk.reshape(b, h, t, d),
        dv.reshape(b, h, t, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret,
           window=0):
    out, _, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret, window=window)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window=0):
    out, l, m = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret, window=window)
    return out, (q, k, v, out, l, m)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window, res,
               g):
    q, k, v, out, l, m = res
    if os.environ.get("ELASTICDL_FLASH_BWD", "pallas") == "xla":
        # Escape hatch: the XLA block-recompute backward.
        return _blockwise_bwd(q, k, v, out, l, m, g, causal, scale,
                              block_k, window=window)
    return _pallas_bwd(q, k, v, out, l, m, g, causal, scale, interpret,
                       window=window)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _check_window(window, causal):
    if window and not causal:
        raise ValueError("sliding window requires causal attention")
    if window < 0:
        raise ValueError("window must be >= 0, got %d" % window)


def _friendly(t, d, block_q, block_k):
    # block_k must equal STATS_LANES so the kernel's [bq, bk] score tile
    # is lane-aligned with the [bq, STATS_LANES] running stats.
    return block_k == STATS_LANES and not (
        t % block_q or t % block_k or (d % 128 and d != 64)
    )


def flash_attention(q, k, v, causal=True, scale=None, block_q=128,
                    block_k=128, interpret=False, window=0):
    """q, k, v: [batch, heads, seq, head_dim].  ``window`` > 0 limits
    causal attention to the last ``window`` positions (O(T·W) compute:
    blocks outside the band skip both matmuls and DMA)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    _check_window(window, causal)
    t = q.shape[2]
    d = q.shape[3]
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if not _friendly(t, d, block_q, block_k):
        return _attention_ref(q, k, v, causal, scale, window=window)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret,
                  window)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_partial(q, k, v, causal, scale, block_q, block_k, interpret,
                   k_offset, window):
    # causal here means the diagonal (k_offset == 0) block, where the
    # kernel's absolute-position mask equals the local mask.
    out, l, m = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret, normalize=False,
        window=window,
    )
    return out, l, m


def _partial_ref(q, k, v, causal, scale, k_offset, window=0):
    """Unnormalized block attention in jnp (ring-fold fallback and the
    recompute target of the partial bwd).  Positions: q rows are local,
    k rows offset by ``k_offset`` (ring rotation); ``window`` > 0 keeps
    only q_pos - k_pos in [0, window)."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        diff = (
            jnp.arange(tq)[:, None] - (k_offset + jnp.arange(tk))[None, :]
        )
        mask = diff >= 0
        if window:
            mask &= diff < window
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = s.max(axis=-1)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    acc = jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return acc, l, m


def _partial_banded(q, k, v, scale, k_offset, window, block_k=128):
    """Causal banded partial for a TRACED ``k_offset`` (the ring's
    window-straddling block, where the offset depends on the device
    rank).  Scans K blocks with the online-softmax fold and
    ``jax.checkpoint`` on the per-block math, so live memory is
    O(T·block_k) in both directions — never the dense [T, T_k] square
    the jnp reference would materialize.  Falls back to ``_partial_ref``
    when T_k doesn't divide into blocks."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    if tk % block_k or tk // block_k <= 1:
        return _partial_ref(q, k, v, True, scale, k_offset, window=window)
    qf = q.astype(jnp.float32)
    q_pos = jnp.arange(tq)
    num_k, k_blocks, v_blocks = _kv_blocks(k, v, block_k)

    @jax.checkpoint
    def block(ki, kb, vb):
        s, _ = _masked_block_scores(
            qf, kb, ki, block_k, True, scale, k_offset, q_pos,
            window=window,
        )
        m_i = s.max(axis=-1)
        p = jnp.exp(s - m_i[..., None])
        l_i = p.sum(axis=-1)
        acc_i = jnp.einsum(
            "bhqk,bhkd->bhqd", p, vb,
            preferred_element_type=jnp.float32,
        )
        return acc_i, l_i, m_i

    def body(carry, inputs):
        o, l, m = carry
        ki, kb, vb = inputs
        acc_i, l_i, m_i = block(ki, kb, vb)
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        return (
            o * alpha[..., None] + acc_i * beta[..., None],
            l * alpha + l_i * beta,
            m_new,
        ), None

    init = (
        jnp.zeros((b, h, tq, d), jnp.float32),
        jnp.zeros((b, h, tq), jnp.float32),
        jnp.full((b, h, tq), NEG_INF, jnp.float32),
    )
    (o, l, m), _ = jax.lax.scan(
        body, init, (jnp.arange(num_k), k_blocks, v_blocks)
    )
    return o, l, m


def _partial_stats_bwd(q, k, v, acc, l, ga, gl, gm, causal, scale,
                       k_offset, block_k, window=0):
    """Hand-written backward of ``(acc, l, m) = partial(q, k, v)`` that
    walks K in blocks, recomputing each [T, block_k] score tile — live
    memory is O(T x block_k) plus the O(T x D) grad accumulators, never
    the dense [T, T_k] square (nor scan-vjp carry residuals).

    With e_ij = exp(s_ij - m_i) the pullback of cotangents
    (ga, gl, gm) is
        ds_ij = e_ij (ga_i . v_j + gl_i) + (ind_ij / cnt_i) c_i,
        c_i   = gm_i - ga_i . acc_i - gl_i l_i,
        dv_j  = sum_i e_ij ga_i,   dq = scale ds k,   dk = scale ds^T q,
    where ind marks the row-max positions and cnt splits ties the way
    reduce_max's vjp does.  m is deliberately NOT taken from the saved
    kernel stats: it is recomputed (pass 1) from the same jnp scores
    pass 3 uses, so the ``s == m_re`` indicator compares bit-identical
    values (kernel-vs-jnp ulp differences would silently drop the gm
    cotangent).  Saved acc/l feed the c coefficient only.
    """
    b, h, tq, d = q.shape
    qf = q.astype(jnp.float32)
    q_pos = jnp.arange(tq)
    num_k, k_blocks, v_blocks = _kv_blocks(k, v, block_k)
    gaf = ga.astype(jnp.float32)

    def scores(ki, kb):
        return _masked_block_scores(
            qf, kb, ki, block_k, causal, scale, k_offset, q_pos,
            window=window,
        )

    # Pass 1: row max, recomputed so pass 3's indicator is exact.
    def max_body(m_c, inputs):
        ki, kb = inputs
        s, _ = scores(ki, kb)
        return jnp.maximum(m_c, s.max(axis=-1)), None

    m_re, _ = jax.lax.scan(
        max_body, jnp.full((b, h, tq), NEG_INF, jnp.float32),
        (jnp.arange(num_k), k_blocks),
    )

    # Pass 2: tie count at the max (reduce_max's vjp splits ties).
    def cnt_body(cnt, inputs):
        ki, kb = inputs
        s, _ = scores(ki, kb)
        return cnt + (s == m_re[..., None]).sum(axis=-1), None

    cnt, _ = jax.lax.scan(
        cnt_body, jnp.zeros((b, h, tq), jnp.int32),
        (jnp.arange(num_k), k_blocks),
    )

    c = (
        gm.astype(jnp.float32)
        - jnp.einsum("bhqd,bhqd->bhq", gaf, acc.astype(jnp.float32))
        - gl.astype(jnp.float32) * l.astype(jnp.float32)
    ) / jnp.maximum(cnt, 1).astype(jnp.float32)

    # Pass 3: grads, one K block at a time.
    def grad_body(dq, inputs):
        ki, kb, vb = inputs
        s, mask = scores(ki, kb)
        e = jnp.exp(s - m_re[..., None])               # [B,H,T,bk]
        ds = e * (
            jnp.einsum("bhqd,bhkd->bhqk", gaf, vb,
                       preferred_element_type=jnp.float32)
            + gl.astype(jnp.float32)[..., None]
        ) + jnp.where(s == m_re[..., None], c[..., None], 0.0)
        if mask is not None:
            # the dense vjp drops gradient at masked positions (the
            # `where` in the forward); mirror it for exact parity
            ds = jnp.where(mask, ds, 0.0)
        dv = jnp.einsum("bhqk,bhqd->bhkd", e, gaf)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
        dq = dq + jnp.einsum(
            "bhqk,bhkd->bhqd", ds, kb,
            preferred_element_type=jnp.float32,
        ) * scale
        return dq, (dk, dv)

    dq, (dk, dv) = jax.lax.scan(
        grad_body, jnp.zeros((b, h, tq, d), jnp.float32),
        (jnp.arange(num_k), k_blocks, v_blocks),
    )
    dk = jnp.moveaxis(dk, 0, 2).reshape(k.shape)
    dv = jnp.moveaxis(dv, 0, 2).reshape(v.shape)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_partial_fwd(q, k, v, causal, scale, block_q, block_k,
                       interpret, k_offset, window):
    out = _flash_partial(q, k, v, causal, scale, block_q, block_k,
                         interpret, k_offset, window)
    acc, l, _ = out
    return out, (q, k, v, acc, l)


def _flash_partial_bwd(causal, scale, block_q, block_k, interpret,
                       k_offset, window, res, g):
    q, k, v, acc, l = res
    ga, gl, gm = g
    tk = k.shape[2]
    if tk % block_k == 0 and tk // block_k > 1:
        return _partial_stats_bwd(
            q, k, v, acc, l, ga, gl, gm, causal, scale, k_offset,
            block_k, window=window,
        )
    _, vjp = jax.vjp(
        lambda q, k, v: _partial_ref(q, k, v, causal, scale, k_offset,
                                     window=window),
        q, k, v,
    )
    return vjp((ga, gl, gm))


_flash_partial.defvjp(_flash_partial_fwd, _flash_partial_bwd)


def flash_attention_partial(q, k, v, causal=True, scale=None, k_offset=0,
                            block_q=128, block_k=128, interpret=False,
                            window=0):
    """Unnormalized online-softmax block attention: returns
    (acc [B,H,T,D] f32, l [B,H,T] f32, m [B,H,T] f32) for this KV block,
    ready to fold into a running (o, l, m) state — the per-shard step of
    ring attention.  Causal masking compares local q rows against k rows
    shifted by ``k_offset``.

    The Pallas kernel serves k_offset == 0 (the ring's diagonal block,
    where absolute and local positions coincide) and every non-causal
    block; a non-zero offset (not needed by the ring's dispatch, which
    routes lower blocks as non-causal and skips upper ones) uses the jnp
    reference."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    _check_window(window, causal)
    t, d = q.shape[2], q.shape[3]
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if (causal and k_offset != 0) or not _friendly(t, d, block_q, block_k):
        return _partial_ref(q, k, v, causal, scale, k_offset,
                            window=window)
    return _flash_partial(q, k, v, causal, scale, block_q, block_k,
                          interpret, k_offset, window)
