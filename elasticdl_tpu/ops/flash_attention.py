"""Pallas flash attention (forward) for TPU.

The hot op of the long-context path.  One (batch*head, q-block) program
holds its query tile in VMEM and streams K/V tiles of the same head
through the MXU with the online-softmax accumulation, so the T x T score
matrix never materializes in HBM.  Backward currently recomputes with the
jnp reference implementation via custom_vjp (a dedicated bwd kernel is a
later optimization); forward-only paths (serving, evaluation) get the full
benefit.

Layout: [batch, heads, seq, head_dim].  Sequence and head_dim should be
multiples of the block sizes (128 lanes); `flash_attention` falls back to
the reference implementation for unfriendly shapes.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _attention_ref(q, k, v, causal, scale):
    """jnp reference in the same [B, H, T, D] layout."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale):
    # q_ref: [1, block_q, D]; k_ref/v_ref: [1, T, D]; o_ref: [1, block_q, D]
    block_q = q_ref.shape[1]
    seq_len = k_ref.shape[1]
    head_dim = q_ref.shape[2]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale          # [bq, D]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    num_k = seq_len // block_k

    def body(ki, carry):
        acc, l, m = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [bq, bk]
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l = l * alpha + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha[:, None] + pv
        return acc, l, m_new

    acc = jnp.zeros((block_q, head_dim), jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    acc, l, m = jax.lax.fori_loop(0, num_k, body, (acc, l, m))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, t, d = q.shape
    bh = b * h
    qr = q.reshape(bh, t, d)
    kr = k.reshape(bh, t, d)
    vr = v.reshape(bh, t, d)
    grid = (bh, t // block_q)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=block_k, causal=causal, scale=scale
        ),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, t, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                         interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _attention_ref(q, k, v, causal, scale), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=True, scale=None, block_q=128,
                    block_k=128, interpret=False):
    """q, k, v: [batch, heads, seq, head_dim]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    t = q.shape[2]
    d = q.shape[3]
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k or d % 128 and d not in (64, 128, 256):
        return _attention_ref(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
