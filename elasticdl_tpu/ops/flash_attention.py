"""Pallas flash attention for TPU.

The hot op of the long-context path.  One (batch*head, q-block) program
holds its query tile in VMEM and streams K/V tiles of the same head
through the MXU with the online-softmax accumulation, so the T x T score
matrix never materializes in HBM.

Forward emits the per-row softmax stats (l, m) alongside the output, and
the backward is a *block-recompute* pass: a ``lax.scan`` over K blocks
rebuilds each [T, block_k] probability tile from the saved stats and
accumulates dq/dk/dv, so peak memory stays O(T·block_k) — never the full
T x T (VERDICT r1 #5; replaces the old full jnp-recompute bwd).

``flash_attention_partial`` exposes the same kernel without the final
normalization, returning (acc, l, m) for one KV block — the building
block ring attention folds across ``ppermute`` hops
(parallel/ring_attention.py).  The ring's *forward* thereby skips the
dense per-shard score matrix; its backward currently recomputes each
ring step densely ([T/sp x T/sp] per step — bounded by the shard, the
same peak as the jnp fold).  A blockwise partial bwd using the saved
stats is a later optimization.

Layout: [batch, heads, seq, head_dim].  Sequence and head_dim should be
multiples of the block sizes (128 lanes); `flash_attention` falls back to
the reference implementation for unfriendly shapes.  Mode selection (the
relay in this image cannot compile Pallas — see PARITY.md):
``ELASTICDL_FLASH=auto`` (default: compiled kernel on TPU, jnp
elsewhere), ``interpret`` (Pallas interpret mode, for tests), ``off``.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def flash_mode():
    """"tpu" (compiled), "interpret", or "off" for the current config."""
    mode = os.environ.get("ELASTICDL_FLASH", "auto")
    if mode == "auto":
        return "tpu" if jax.default_backend() == "tpu" else "off"
    return mode


def _attention_ref(q, k, v, causal, scale):
    """jnp reference in the same [B, H, T, D] layout."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, m_ref, *, block_k,
                  causal, scale, normalize):
    # q_ref: [1, block_q, D]; k_ref/v_ref: [1, T, D];
    # o_ref: [1, block_q, D]; l_ref/m_ref: [1, block_q]
    block_q = q_ref.shape[1]
    seq_len = k_ref.shape[1]
    head_dim = q_ref.shape[2]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale          # [bq, D]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    num_k = seq_len // block_k

    def body(ki, carry):
        acc, l, m = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [bq, bk]
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l = l * alpha + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha[:, None] + pv
        return acc, l, m_new

    acc = jnp.zeros((block_q, head_dim), jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    acc, l, m = jax.lax.fori_loop(0, num_k, body, (acc, l, m))
    if normalize:
        o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(
            o_ref.dtype
        )
    else:
        o_ref[0] = acc.astype(o_ref.dtype)
    l_ref[0] = l
    m_ref[0] = m


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                   normalize=True):
    """Returns (out, l, m); out is normalized iff ``normalize``."""
    b, h, t, d = q.shape
    bh = b * h
    qr = q.reshape(bh, t, d)
    kr = k.reshape(bh, t, d)
    vr = v.reshape(bh, t, d)
    grid = (bh, t // block_q)
    out_dtype = q.dtype if normalize else jnp.float32
    out, l, m = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=block_k, causal=causal, scale=scale,
            normalize=normalize,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t, d), out_dtype),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(qr, kr, vr)
    return (
        out.reshape(b, h, t, d),
        l.reshape(b, h, t),
        m.reshape(b, h, t),
    )


def _blockwise_bwd(q, k, v, out, l, m, g, causal, scale, block_k):
    """Block-recompute backward: scan over K blocks rebuilding each
    [T, block_k] probability tile from the saved (l, m) stats.  Peak
    live memory O(B·H·T·block_k), never the T x T matrix."""
    _, _, tk, _ = k.shape
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    outf = out.astype(jnp.float32)
    # delta_i = sum_d dO_i O_i  (the usual flash-bwd row constant)
    delta = (gf * outf).sum(axis=-1)                    # [B,H,T]
    l_safe = jnp.maximum(l, 1e-30)
    q_pos = jnp.arange(q.shape[2])

    num_k = tk // block_k
    k_blocks = k.reshape(*k.shape[:2], num_k, block_k, k.shape[3])
    v_blocks = v.reshape(*v.shape[:2], num_k, block_k, v.shape[3])

    def body(carry, inputs):
        dq = carry
        ki, kb, vb = inputs
        kf = kb.astype(jnp.float32)
        vf = vb.astype(jnp.float32)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, kf,
            preferred_element_type=jnp.float32,
        ) * scale                                       # [B,H,T,bk]
        if causal:
            k_pos = ki * block_k + jnp.arange(block_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        p = jnp.exp(s - m[..., None]) / l_safe[..., None]
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vf)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq, (dk, dv)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    ks = jnp.arange(num_k)
    dq, (dk, dv) = jax.lax.scan(
        body, dq0,
        (ks,
         jnp.moveaxis(k_blocks, 2, 0),
         jnp.moveaxis(v_blocks, 2, 0)),
    )
    dk = jnp.moveaxis(dk, 0, 2).reshape(k.shape)
    dv = jnp.moveaxis(dv, 0, 2).reshape(v.shape)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, l, m = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret)
    return out, (q, k, v, out, l, m)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, l, m = res
    return _blockwise_bwd(q, k, v, out, l, m, g, causal, scale, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _friendly(t, d, block_q, block_k):
    return not (
        t % block_q or t % block_k or (d % 128 and d not in (64, 128, 256))
    )


def flash_attention(q, k, v, causal=True, scale=None, block_q=128,
                    block_k=128, interpret=False):
    """q, k, v: [batch, heads, seq, head_dim]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    t = q.shape[2]
    d = q.shape[3]
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if not _friendly(t, d, block_q, block_k):
        return _attention_ref(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_partial(q, k, v, causal, scale, block_q, block_k, interpret,
                   k_offset):
    # causal here means the diagonal (k_offset == 0) block, where the
    # kernel's absolute-position mask equals the local mask.
    out, l, m = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret, normalize=False,
    )
    return out, l, m


def _partial_ref(q, k, v, causal, scale, k_offset):
    """Unnormalized block attention in jnp (ring-fold fallback and the
    recompute target of the partial bwd).  Positions: q rows are local,
    k rows offset by ``k_offset`` (ring rotation)."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = (
            jnp.arange(tq)[:, None] >= (k_offset + jnp.arange(tk))[None, :]
        )
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = s.max(axis=-1)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    acc = jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return acc, l, m


def _flash_partial_fwd(q, k, v, causal, scale, block_q, block_k,
                       interpret, k_offset):
    out = _flash_partial(q, k, v, causal, scale, block_q, block_k,
                         interpret, k_offset)
    return out, (q, k, v)


def _flash_partial_bwd(causal, scale, block_q, block_k, interpret,
                       k_offset, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _partial_ref(q, k, v, causal, scale, k_offset),
        q, k, v,
    )
    return vjp(g)


_flash_partial.defvjp(_flash_partial_fwd, _flash_partial_bwd)


def flash_attention_partial(q, k, v, causal=True, scale=None, k_offset=0,
                            block_q=128, block_k=128, interpret=False):
    """Unnormalized online-softmax block attention: returns
    (acc [B,H,T,D] f32, l [B,H,T] f32, m [B,H,T] f32) for this KV block,
    ready to fold into a running (o, l, m) state — the per-shard step of
    ring attention.  Causal masking compares local q rows against k rows
    shifted by ``k_offset``.

    The Pallas kernel serves k_offset == 0 (the ring's diagonal block,
    where absolute and local positions coincide) and every non-causal
    block; a non-zero offset (not needed by the ring's dispatch, which
    routes lower blocks as non-causal and skips upper ones) uses the jnp
    reference."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    t, d = q.shape[2], q.shape[3]
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if (causal and k_offset != 0) or not _friendly(t, d, block_q, block_k):
        return _partial_ref(q, k, v, causal, scale, k_offset)
    return _flash_partial(q, k, v, causal, scale, block_q, block_k,
                          interpret, k_offset)
