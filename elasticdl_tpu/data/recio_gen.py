"""Dataset -> recio converters (reference: data/recordio_gen/*).

Records serialize as npz-encoded feature dicts; RecioDataReader decodes
them with ``decode_record``.
"""

import io
import os

import numpy as np

from elasticdl_tpu.data.recio import RecioWriter


def encode_record(**arrays):
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
    return buf.getvalue()


def decode_record(payload):
    with np.load(io.BytesIO(payload)) as z:
        return {k: z[k] for k in z.files}


def decode_xy(payload):
    """Decoder for (x, y) supervised records."""
    d = decode_record(payload)
    return d["x"], d["y"]


def convert_arrays(output_dir, arrays, records_per_file=2048,
                   names=("x", "y")):
    """Write parallel arrays into sharded recio files (one per shard)."""
    os.makedirs(output_dir, exist_ok=True)
    n = len(arrays[0])
    file_index = 0
    written = []
    pos = 0
    while pos < n:
        end = min(pos + records_per_file, n)
        path = os.path.join(
            output_dir, "data-%05d.recio" % file_index
        )
        with RecioWriter(path) as w:
            for i in range(pos, end):
                w.write(encode_record(
                    **{name: a[i] for name, a in zip(names, arrays)}
                ))
        written.append(path)
        file_index += 1
        pos = end
    return written


def convert_synthetic_mnist(output_dir, n=4096, records_per_file=1024):
    from elasticdl_tpu.models.mnist import synthetic_data

    xs, ys = synthetic_data(n=n)
    return convert_arrays(output_dir, (xs, ys),
                          records_per_file=records_per_file)


def convert_csv(csv_path, output_dir, label_column=-1,
                records_per_file=2048, skip_header=False,
                numeric_columns=None):
    """CSV -> recio (x, y) records — the census/heart converter shape
    (reference census_recordio_gen.py / heart_recordio_gen.py): feature
    columns become a float vector, the label column an int32 scalar.

    numeric_columns: indices of columns to keep as features; default =
    every column except the label.  Non-numeric values hash to a float
    bucket (the reference pre-hashes categoricals before packing).
    """
    import csv as _csv

    from elasticdl_tpu.utils.hashing import string_to_id

    rows = []
    with open(csv_path, newline="") as f:
        reader = _csv.reader(f)
        if skip_header:
            next(reader, None)
        for row in reader:
            if row:
                rows.append(row)
    if not rows:
        raise ValueError("no rows in %s" % csv_path)
    ncols = len(rows[0])
    for i, row in enumerate(rows):
        if len(row) != ncols:
            raise ValueError(
                "ragged CSV: row %d has %d columns, expected %d"
                % (i + 1, len(row), ncols)
            )
    if not -ncols <= label_column < ncols:
        raise ValueError(
            "label_column %d out of range for %d columns"
            % (label_column, ncols)
        )
    label_column = label_column % ncols
    if numeric_columns is None:
        numeric_columns = [i for i in range(ncols) if i != label_column]

    import math

    def to_float(v):
        try:
            x = float(v)
        except ValueError:
            return float(string_to_id(v, 1 << 16))
        # literal "nan"/"inf" strings are categorical markers, not
        # features — bucket them like any other string
        return x if math.isfinite(x) else float(string_to_id(v, 1 << 16))

    xs = np.asarray(
        [[to_float(row[i]) for i in numeric_columns] for row in rows],
        np.float32,
    )
    # Labels: all-numeric passes through; all-categorical ('>50K' /
    # '<=50K') gets stable vocabulary ids.  A MIX is ambiguous (one
    # stray '?' would silently renumber every numeric class), so it
    # errors instead of guessing.
    raw_labels = [row[label_column] for row in rows]

    def numeric_label(v):
        try:
            return int(float(v))
        except ValueError:
            return None

    parsed = [numeric_label(v) for v in raw_labels]
    if all(p is not None for p in parsed):
        ys = np.asarray(parsed, np.int32)
    elif all(p is None for p in parsed):
        vocab = {v: i for i, v in enumerate(sorted(set(raw_labels)))}
        ys = np.asarray([vocab[v] for v in raw_labels], np.int32)
    else:
        bad = sorted({
            v for v, p in zip(raw_labels, parsed) if p is None
        })[:5]
        raise ValueError(
            "label column mixes numeric and non-numeric values "
            "(e.g. %s); clean the data or choose another column" % bad
        )
    return convert_arrays(output_dir, (xs, ys),
                          records_per_file=records_per_file)


def convert_ctr(output_dir, n=65536, records_per_file=4096, **kwargs):
    """Synthetic CTR (dense, ids, label) records — the frappe/dac_ctr
    converter shape (reference frappe_recordio_gen.py)."""
    from elasticdl_tpu.models.deepfm import synthetic_data

    dense, ids, labels = synthetic_data(n=n, **kwargs)
    return convert_arrays(
        output_dir, (dense, ids, labels),
        records_per_file=records_per_file, names=("dense", "ids", "y"),
    )
