"""Dataset -> recio converters (reference: data/recordio_gen/*).

Records serialize as npz-encoded feature dicts; RecioDataReader decodes
them with ``decode_record``.
"""

import io
import os

import numpy as np

from elasticdl_tpu.data.recio import RecioWriter


def encode_record(**arrays):
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
    return buf.getvalue()


def decode_record(payload):
    with np.load(io.BytesIO(payload)) as z:
        return {k: z[k] for k in z.files}


def decode_xy(payload):
    """Decoder for (x, y) supervised records."""
    d = decode_record(payload)
    return d["x"], d["y"]


def convert_arrays(output_dir, arrays, records_per_file=2048,
                   names=("x", "y")):
    """Write parallel arrays into sharded recio files (one per shard)."""
    os.makedirs(output_dir, exist_ok=True)
    n = len(arrays[0])
    file_index = 0
    written = []
    pos = 0
    while pos < n:
        end = min(pos + records_per_file, n)
        path = os.path.join(
            output_dir, "data-%05d.recio" % file_index
        )
        with RecioWriter(path) as w:
            for i in range(pos, end):
                w.write(encode_record(
                    **{name: a[i] for name, a in zip(names, arrays)}
                ))
        written.append(path)
        file_index += 1
        pos = end
    return written


def convert_synthetic_mnist(output_dir, n=4096, records_per_file=1024):
    from elasticdl_tpu.models.mnist import synthetic_data

    xs, ys = synthetic_data(n=n)
    return convert_arrays(output_dir, (xs, ys),
                          records_per_file=records_per_file)
