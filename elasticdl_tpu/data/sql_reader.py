"""SQL table reader — the MaxCompute/ODPS reader equivalent.

The reference ships an ODPS (MaxCompute) table reader with schema
metadata, sharded range reads and a writer
(elasticdl/python/data/reader/odps_reader.py:27-120, data/odps_io.py).
The TPU-native build generalizes it to any DB-API database; sqlite3
(stdlib) works out of the box, and warehouse-specific drivers plug in via
``connection_factory``.  Shards are rowid ranges, so dynamic sharding and
task retries behave exactly like file readers.
"""

import sqlite3

import numpy as np

from elasticdl_tpu.data.reader import AbstractDataReader


class SQLTableDataReader(AbstractDataReader):
    def __init__(self, database, table, columns=None,
                 records_per_shard=1000, connection_factory=None):
        self._database = database
        self._table = table
        self._records_per_shard = records_per_shard
        # check_same_thread=False: the worker's prefetch runs
        # read_records in a background thread.  Access is serialized in
        # the normal path (prefetch joins its producer before the next
        # task starts, data/parallel_reader.py); a wedged producer that
        # outlives the 60 s join could race a new one, so keep the
        # guard when the sqlite build does NOT fully serialize
        # connections.  Pre-3.11 the module reports a hardcoded
        # threadsafety of 1 regardless of the build, so trust CPython's
        # serialized default there.
        import sys

        _cst = (
            sys.version_info >= (3, 11) and sqlite3.threadsafety < 3
        )
        self._connect = connection_factory or (
            lambda: sqlite3.connect(database, check_same_thread=_cst)
        )
        self._conn = self._connect()
        cur = self._conn.execute("SELECT COUNT(*) FROM %s" % table)
        self._size = cur.fetchone()[0]
        if columns is None:
            cur = self._conn.execute(
                "SELECT * FROM %s LIMIT 1" % table
            )
            columns = [d[0] for d in cur.description]
        self._columns = columns

    @property
    def columns(self):
        return list(self._columns)

    def get_size(self):
        return self._size

    @property
    def records_per_shard(self):
        return self._records_per_shard

    def create_shards(self):
        shards = []
        start = 0
        while start < self._size:
            end = min(start + self._records_per_shard, self._size)
            shards.append((self._table, start, end))
            start = end
        return shards

    def read_records(self, task):
        if task.shard.record_indices:
            # Shuffled task: the indices are a permutation of the
            # shard's own range, so fetch the covering range in ONE
            # query and reorder in memory — per-index OFFSET queries
            # would rescan the table once per record.
            indices = [int(i) for i in task.shard.record_indices]
            lo, hi = min(indices), max(indices) + 1
            cur = self._conn.execute(
                "SELECT %s FROM %s LIMIT ? OFFSET ?"
                % (", ".join(self._columns), self._table),
                (hi - lo, lo),
            )
            rows = cur.fetchall()
            for i in indices:
                if 0 <= i - lo < len(rows):
                    yield list(rows[i - lo])
            return
        start, end = task.shard.start, task.shard.end
        cur = self._conn.execute(
            "SELECT %s FROM %s LIMIT ? OFFSET ?"
            % (", ".join(self._columns), self._table),
            (end - start, start),
        )
        for row in cur:
            yield list(row)


class SQLTableWriter:
    """Row writer (reference ODPSWriter parity) — batch inserts."""

    def __init__(self, database, table, columns,
                 connection_factory=None):
        self._connect = connection_factory or (
            lambda: sqlite3.connect(database)
        )
        self._conn = self._connect()
        self._table = table
        self._columns = columns
        cols = ", ".join("%s" % c for c in columns)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS %s (%s)" % (table, cols)
        )
        self._insert_sql = "INSERT INTO %s (%s) VALUES (%s)" % (
            table, cols, ", ".join("?" for _ in columns)
        )

    def write(self, rows):
        self._conn.executemany(
            self._insert_sql,
            [tuple(np.asarray(r).tolist()) for r in rows],
        )
        self._conn.commit()

    def close(self):
        self._conn.close()
