"""Image-folder dataset plumbing (the ImageNet-layout path).

Reference counterparts: torchvision-ImageFolder-based ElasticImageFolder
(elasticai_api/pytorch/dataset.py:33-92) and the image recordio
generators (data/recordio_gen/image_label.py).  TPU-native pieces:

 - ``ImageFolderDataReader``: an AbstractDataReader over the standard
   ``root/<class_name>/<image>`` layout.  Shards are index ranges into
   the sorted (path, label) list, so dynamic sharding, retries, and
   shuffle-by-record-indices behave exactly like every other reader.
   Decode = PIL -> RGB -> resize -> float32 [H, W, 3] in [0, 1], done
   on the host; batches then feed the jitted step as one contiguous
   device_put (keep per-image work on the host, the MXU never sees
   JPEG bytes).
 - ``ElasticImageFolder``: map-style dataset whose __getitem__ consumes
   master-assigned indices (api/dataset.py ElasticDataset over the
   folder source) — drop-in for a stock torch DataLoader loop.
 - ``pack_image_folder``: offline packing of the folder into recio
   files (decode once, train many) via data/recio_gen's npz payloads.
"""

import os

import numpy as np

from elasticdl_tpu.data.reader import AbstractDataReader

_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


def scan_image_folder(root):
    """-> (samples [(path, label_id)], class_names sorted)."""
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d))
    )
    if not classes:
        raise ValueError("no class directories under %r" % root)
    samples = []
    for label, name in enumerate(classes):
        class_dir = os.path.join(root, name)
        # os.listdir, not glob: dataset paths with glob metacharacters
        # ("run[1]") must not silently drop images.
        for fname in sorted(os.listdir(class_dir)):
            if fname.lower().endswith(_EXTENSIONS):
                samples.append((os.path.join(class_dir, fname), label))
    if not samples:
        raise ValueError("no images under %r" % root)
    return samples, classes


def load_image(path, image_size):
    from PIL import Image

    with Image.open(path) as img:
        img = img.convert("RGB")
        if image_size:
            img = img.resize((image_size, image_size))
        return np.asarray(img, np.float32) / 255.0


def augment_image(img, rng, crop_padding=0.125):
    """Standard training augmentation: random resized crop (pad-style
    — the image is upscaled by ``crop_padding`` and a random
    image-size window is taken) + random horizontal flip.

    Pure numpy on the HOST, inside the data plane's prefetch threads —
    augmentation must never be traced into the jitted step (it would
    either freeze the randomness as constants or force per-step
    recompiles; the reference likewise augments in the input
    pipeline).  Output shape equals input shape, so the static-shape
    contract of the compiled step is untouched.
    """
    h, w = img.shape[:2]
    pad_h = int(round(h * crop_padding))
    pad_w = int(round(w * crop_padding))
    if pad_h and pad_w:
        padded = np.pad(
            img, ((pad_h, pad_h), (pad_w, pad_w), (0, 0)),
            mode="reflect")
        top = rng.randint(0, 2 * pad_h + 1)
        left = rng.randint(0, 2 * pad_w + 1)
        img = padded[top:top + h, left:left + w]
    if rng.rand() < 0.5:
        img = img[:, ::-1]
    return np.ascontiguousarray(img)


class ImageFolderDataReader(AbstractDataReader):
    def __init__(self, root, image_size=224, records_per_shard=1024,
                 augment=False, seed=None):
        """``seed=None`` (default) draws fresh OS entropy per process —
        N workers (and every relaunch) must NOT replay one identical
        augmentation stream; pass a seed only for reproducibility in
        tests."""
        self._root = root
        self._image_size = image_size
        self._records_per_shard = records_per_shard
        self._augment = augment
        self._rng = np.random.RandomState(seed)
        self.samples, self.class_names = scan_image_folder(root)

    @property
    def records_per_shard(self):
        return self._records_per_shard

    def num_classes(self):
        return len(self.class_names)

    def get_size(self):
        return len(self.samples)

    def create_shards(self):
        shards = []
        start = 0
        n = len(self.samples)
        while start < n:
            end = min(start + self._records_per_shard, n)
            shards.append((self._root, start, end))
            start = end
        return shards

    def _record(self, i, augment):
        path, label = self.samples[i]
        img = load_image(path, self._image_size)
        if augment:
            img = augment_image(img, self._rng)
        return img, label

    def read_records(self, task):
        from elasticdl_tpu.proto import elastic_pb2 as pb

        # Augment TRAINING records only: evaluation/prediction through
        # the same reader must see the raw images (random crops would
        # make validation metrics noisy and non-reproducible).
        augment = self._augment and (
            getattr(task, "type", pb.TRAINING) == pb.TRAINING
        )
        indices = task.shard.record_indices or range(
            task.shard.start, min(task.shard.end, len(self.samples))
        )
        for i in indices:
            yield self._record(i, augment)


class ElasticImageFolder:
    """Stock-DataLoader-compatible elastic dataset over an image folder
    (reference ElasticImageFolder semantics: __getitem__ pulls the next
    master-assigned record index; __len__ is unbounded)."""

    def __init__(self, root, master_client, image_size=224,
                 batch_size=1):
        from elasticdl_tpu.api.dataset import ElasticDataset

        self._reader = ImageFolderDataReader(root, image_size=image_size)
        self._elastic = ElasticDataset(
            _IndexableFolder(self._reader), master_client,
            batch_size=batch_size,
        )
        self.class_names = self._reader.class_names

    def __len__(self):
        return len(self._elastic)

    def __getitem__(self, index):
        return self._elastic[index]

    def report_batch_done(self, batch_size=None):
        self._elastic.report_batch_done(batch_size)

    def stop(self):
        self._elastic.stop()


class _IndexableFolder:
    def __init__(self, reader):
        self._reader = reader

    def __getitem__(self, i):
        # Torch-style training dataset: augment iff the reader asks.
        return self._reader._record(i, self._reader._augment)


def pack_image_folder(root, output_dir, image_size=224,
                      records_per_file=1024):
    """Decode once, train many: pack the folder into recio files of
    npz-encoded (x [H,W,3] f32, y int32) records."""
    from elasticdl_tpu.data.recio import RecioWriter
    from elasticdl_tpu.data.recio_gen import encode_record

    samples, classes = scan_image_folder(root)
    os.makedirs(output_dir, exist_ok=True)
    writer = None
    file_idx = count = 0
    for path, label in samples:
        if writer is None:
            writer = RecioWriter(
                os.path.join(output_dir, "images-%05d.recio" % file_idx)
            )
        writer.write(encode_record(
            x=load_image(path, image_size),
            y=np.asarray(label, np.int32),
        ))
        count += 1
        if count % records_per_file == 0:
            writer.close()
            writer = None
            file_idx += 1
    if writer is not None:
        writer.close()
    with open(os.path.join(output_dir, "classes.txt"), "w") as f:
        f.write("\n".join(classes))
    return count, classes
