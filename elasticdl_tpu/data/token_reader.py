"""Memory-mapped binary token files — the LM-native data path.

The GPT-style pretraining layout: a flat binary file of token ids
(uint16 for vocab <= 65536, uint32 otherwise), read as fixed-length
non-overlapping windows.  TPU-first properties:

 - **Zero-copy reads**: ``np.memmap`` — a task's window slice touches
   only its own pages; no parse, no decode, no Python-object records.
   One 4-byte-token 2048-seq record is 8 KB of sequential IO.
 - **Exact dynamic sharding**: a record IS a window, so the task
   stream's [start, end) ranges map to byte offsets directly — any
   worker can serve any shard, and elastic re-queues lose nothing.
 - **Resume-friendly**: skip_records (master resume) is a pure index
   offset.

Factory origin: ``tokens:<path>:<seq_len>[:<dtype>]`` (dtype uint16 |
uint32, default uint16).  ``write_token_file`` is the matching writer
(tokenizer output -> training file).

Parity: the role of the reference's RecordIO/Text readers
(data/reader/data_reader.py:65-105) for the token-stream modality the
reference never had.
"""

import os

import numpy as np

from elasticdl_tpu.data.reader import AbstractDataReader


def write_token_file(path, tokens, dtype=np.uint16):
    """Append-or-create a flat binary token file from an id array.

    The format is headerless, so a mixed-dtype append would silently
    byte-misalign every later window: the dtype used at creation is
    recorded in a ``<path>.meta`` sidecar and appends must match it.
    """
    tokens = np.asarray(tokens)
    if tokens.size == 0:
        return  # empty document in a tokenize-and-append loop
    dtype = np.dtype(dtype)
    meta_path = path + ".meta"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            recorded = f.read().strip()
        if recorded != dtype.name:
            raise ValueError(
                "token file %s was created with dtype %s; appending "
                "%s would corrupt it" % (path, recorded, dtype.name))
    else:
        with open(meta_path, "w") as f:
            f.write(dtype.name)
    info = np.iinfo(dtype)
    if tokens.min() < info.min or tokens.max() > info.max:
        raise ValueError(
            "token ids [%d, %d] exceed %s range"
            % (tokens.min(), tokens.max(), dtype.name))
    with open(path, "ab") as f:
        tokens.astype(dtype).ravel().tofile(f)


class TokenFileDataReader(AbstractDataReader):
    def __init__(self, path, seq_len, dtype=np.uint16,
                 records_per_shard=256):
        self._path = path
        self._seq_len = int(seq_len)
        self._dtype = np.dtype(dtype)
        meta_path = path + ".meta"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                recorded = f.read().strip()
            if recorded != self._dtype.name:
                raise ValueError(
                    "token file %s records dtype %s (sidecar); reader "
                    "asked for %s" % (path, recorded, self._dtype.name))
        self._records_per_shard = records_per_shard
        n_tokens = os.path.getsize(path) // self._dtype.itemsize
        # trailing partial window is dropped (a short record would
        # break the static [B, T] shape every jitted step relies on)
        self._num_records = n_tokens // self._seq_len
        self._mmap = None

    @property
    def records_per_shard(self):
        return self._records_per_shard

    def create_shards(self):
        shards = []
        for start in range(0, self._num_records,
                           self._records_per_shard):
            end = min(start + self._records_per_shard,
                      self._num_records)
            shards.append((self._path, start, end))
        return shards

    def read_records(self, task):
        if self._mmap is None:
            # Lazy: workers construct the reader before forking
            # subprocesses; an inherited mmap handle is not fork-safe.
            self._mmap = np.memmap(self._path, dtype=self._dtype,
                                   mode="r")
        T = self._seq_len
        # record_indices: the task manager's shuffle permutation (and
        # its resume-trimmed tail) — every reader must honor it or
        # --shuffle silently no-ops and resume diverges.
        indices = task.shard.record_indices or range(
            task.shard.start, task.shard.end)
        n_tokens = len(self._mmap)
        for idx in indices:
            # Fail loudly on a truncated file or stale shard range: a
            # silent short slice would break the static [B, T] batch
            # shape downstream (ADVICE r5 low).
            if idx < 0 or (idx + 1) * T > n_tokens:
                raise ValueError(
                    "token shard window %d of %s is out of range: "
                    "[%d:%d) exceeds the file's %d tokens — truncated "
                    "file or stale shard metadata?"
                    % (idx, self._path, idx * T, (idx + 1) * T,
                       n_tokens))
            window = self._mmap[idx * T:(idx + 1) * T]
            yield (np.asarray(window, dtype=np.int32),)
