"""`recio` — a minimal length-prefixed binary record format.

The TPU-native stand-in for the reference's RecordIO dependency
(elasticdl/python/data/reader/recordio_reader.py:27-63): sequential records,
random access by record index, one file per shard.  Format:

    magic b"ETPR" | uint32 version | records: (uint32 length | payload)*

Record offsets are recovered with a single sequential scan at open time and
cached, giving O(1) seeks for [start, end) shard reads.
"""

import io
import os
import struct

MAGIC = b"ETPR"
VERSION = 1
_LEN = struct.Struct("<I")


class RecioWriter:
    def __init__(self, path):
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._f.write(_LEN.pack(VERSION))

    def write(self, payload):
        if not isinstance(payload, (bytes, bytearray)):
            raise TypeError("recio records are bytes, got %r" % type(payload))
        self._f.write(_LEN.pack(len(payload)))
        self._f.write(payload)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecioReader:
    def __init__(self, path):
        self._path = path
        self._f = open(path, "rb")
        header = self._f.read(8)
        if header[:4] != MAGIC:
            raise ValueError("not a recio file: %s" % path)
        self._offsets = []
        self._scan()

    def _scan(self):
        f = self._f
        f.seek(8, io.SEEK_SET)
        size = os.fstat(f.fileno()).st_size
        pos = 8
        while pos < size:
            self._offsets.append(pos)
            (length,) = _LEN.unpack(f.read(4))
            pos += 4 + length
            f.seek(pos, io.SEEK_SET)

    def __len__(self):
        return len(self._offsets)

    def read(self, index):
        self._f.seek(self._offsets[index], io.SEEK_SET)
        (length,) = _LEN.unpack(self._f.read(4))
        return self._f.read(length)

    def read_range(self, start, end):
        for i in range(start, min(end, len(self._offsets))):
            yield self.read(i)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
