"""Multiprocess sharded reading + batch prefetching.

The reference's ``odps_io`` runs a pool of reader processes over table
shards with retrying batch reads and streams records to the trainer
(elasticdl/python/data/odps_io.py:71-400).  TPU-native equivalent, two
pieces:

 - ``ParallelShardReader``: wraps any AbstractDataReader *factory* in a
   multiprocessing pool.  A task's [start, end) range splits into
   sub-ranges; each pool process lazily builds its own reader (DB
   connections and file handles don't survive fork) and reads one
   sub-range per job, with bounded retries on transient read errors.
   Records come back in range order.

 - ``prefetch_batches``: a background-thread iterator that keeps N
   batches ready so host-side feed/decode overlaps device compute — the
   input-pipeline half of keeping the MXU busy (the device half is the
   jitted step; see worker/worker.py).

Both compose with the reader factory (data/factory.py) and the dynamic
sharding protocol unchanged: the master still hands out coarse tasks,
and parallelism here is *within* one worker's task.
"""

import multiprocessing as mp
import queue
import threading
import time
from types import SimpleNamespace

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Per-process reader cache: one reader per pool process, built lazily
# from the factory shipped with each job (factories must be picklable).
_PROC_READER = None
_PROC_FACTORY_ID = None


def _make_task(shard_name, start, end, record_indices=None):
    return SimpleNamespace(
        shard=SimpleNamespace(
            name=shard_name, start=start, end=end,
            record_indices=record_indices or [],
        )
    )


def _read_subrange(args):
    """Pool worker: read one sub-range with bounded retries."""
    global _PROC_READER, _PROC_FACTORY_ID
    factory, factory_key, shard_name, start, end, indices, max_retries = args
    if _PROC_READER is None or _PROC_FACTORY_ID != factory_key:
        _PROC_READER = factory()
        _PROC_FACTORY_ID = factory_key
    task = _make_task(shard_name, start, end, indices)
    last_err = None
    for attempt in range(max_retries):
        try:
            if _PROC_READER is None:
                _PROC_READER = factory()
            return list(_PROC_READER.read_records(task))
        except Exception as e:  # noqa: BLE001 — transient IO/DB errors
            last_err = e
            logger.warning(
                "read [%s, %d, %d) attempt %d failed: %s",
                shard_name, start, end, attempt + 1, e,
            )
            # The reader itself may be the broken part (dropped DB
            # connection): drop it so the next attempt rebuilds inside
            # the try (a factory that throws still counts against the
            # retry budget instead of escaping the loop).
            _PROC_READER = None
            time.sleep(min(2.0 ** attempt * 0.1, 2.0))
    raise RuntimeError(
        "read of [%s, %d, %d) failed after %d attempts: %s"
        % (shard_name, start, end, max_retries, last_err)
    )


class ParallelShardReader:
    """Fan a task's record range out over a process pool.

    reader_factory: picklable zero-arg callable returning an
        AbstractDataReader (e.g. ``functools.partial(SQLTableDataReader,
        db, table)``).
    """

    def __init__(self, reader_factory, num_processes=4,
                 records_per_subrange=256, max_retries=3):
        import pickle

        self._factory = reader_factory
        # Stable identity across pickling so pool processes reuse their
        # reader between jobs instead of reconnecting per sub-range.
        self._factory_key = hash(pickle.dumps(reader_factory))
        self._num_processes = num_processes
        self._per_subrange = records_per_subrange
        self._max_retries = max_retries
        ctx = mp.get_context("spawn")  # fork + grpc/jax threads = hangs
        self._pool = ctx.Pool(num_processes)

    def read_records(self, task):
        """Yield the task's records in order, read by the pool."""
        shard = task.shard
        if shard.record_indices:
            # Shuffled tasks: split the index list itself.
            chunks = [
                (self._factory, self._factory_key, shard.name,
                 shard.start, shard.end,
                 list(shard.record_indices[i:i + self._per_subrange]),
                 self._max_retries)
                for i in range(0, len(shard.record_indices),
                               self._per_subrange)
            ]
        else:
            chunks = []
            start = shard.start
            while start < shard.end:
                end = min(start + self._per_subrange, shard.end)
                chunks.append(
                    (self._factory, self._factory_key, shard.name,
                     start, end, None, self._max_retries)
                )
                start = end
        for records in self._pool.imap(_read_subrange, chunks):
            yield from records

    def close(self):
        self._pool.terminate()
        self._pool.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def prefetch_batches(batch_iter, depth=2, prepare=None):
    """Run ``batch_iter`` in a background thread, keeping up to
    ``depth`` batches ready — host feed/decode overlaps device compute.

    ``prepare`` (optional) maps each item on the PRODUCER thread before
    it is enqueued — the fused training driver passes the trainer's
    ``prepare_batch`` here so padding/reshape/globalize host work runs
    in this pipeline stage instead of on the dispatch critical path
    (docs/training_pipeline.md).  A prepare failure re-raises at the
    consumer like any producer error.

    Exceptions from the producer re-raise at the consumer's next pull,
    so failures surface in the training loop (where the minibatch retry
    machinery lives), not in a daemon thread.
    """
    q = queue.Queue(maxsize=depth)
    _END = object()
    abandoned = threading.Event()

    def _put(item):
        # Bounded put that notices an abandoned consumer: without this,
        # a training loop that breaks early would leave the producer
        # blocked on the full queue forever, pinning batch_iter's
        # resources (pools, DB connections) for the process lifetime.
        while not abandoned.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for batch in batch_iter:
                if prepare is not None:
                    batch = prepare(batch)
                if not _put(batch):
                    return
            _put(_END)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            _put(e)
        finally:
            close = getattr(batch_iter, "close", None)
            if abandoned.is_set() and close is not None:
                close()

    thread = threading.Thread(
        target=produce, name="batch-prefetch", daemon=True
    )
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        abandoned.set()
        # Join before returning control: the caller may immediately
        # start the next task over the SAME stateful reader (shared
        # file handles, seek+read), and two producer threads
        # interleaving on it would tear records.  The producer notices
        # abandonment between batches, so this waits at most one batch
        # read/decode.
        thread.join(timeout=60.0)
        if thread.is_alive():
            # Fail loudly: returning control would let the caller start
            # the next task over the SAME stateful reader while this
            # thread is still mid-read — torn records.  A wedged reader
            # should fail the task (the master re-queues it), not
            # corrupt the next one.
            raise RuntimeError(
                "batch-prefetch producer still running after 60s; "
                "reader wedged — failing the task instead of racing "
                "the next one"
            )
