"""Data readers: map a task's shard to a record stream.

Same contract as the reference's AbstractDataReader
(elasticdl/python/data/reader/data_reader.py:65-105): ``create_shards`` tells
the TaskManager how to partition the dataset; ``read_records`` streams the
records of one task's [start, end) range.  Readers are deliberately
numpy-first: records decode to ndarrays that feed straight into jitted steps.
"""

import abc
import csv
import glob
import os

import numpy as np

from elasticdl_tpu.data.recio import RecioReader


class AbstractDataReader(abc.ABC):
    @abc.abstractmethod
    def create_shards(self):
        """Return a list of (name, start, end) record ranges."""

    @abc.abstractmethod
    def read_records(self, task):
        """Yield records for task.shard's [start, end) range."""

    @property
    def records_per_shard(self):
        return None


class RecioDataReader(AbstractDataReader):
    """One shard per recio file (reference: recordio_reader.py:27-63)."""

    def __init__(self, data_dir, decode_fn=None):
        self._data_dir = data_dir
        self._decode_fn = decode_fn
        self._readers = {}

    def _reader(self, name):
        if name not in self._readers:
            self._readers[name] = RecioReader(name)
        return self._readers[name]

    def create_shards(self):
        from elasticdl_tpu.data.recio import MAGIC

        shards = []
        for path in sorted(glob.glob(os.path.join(self._data_dir, "*"))):
            if not os.path.isfile(path):
                continue
            with open(path, "rb") as f:
                if f.read(4) != MAGIC:
                    continue  # skip non-recio files in mixed dirs
            shards.append((path, 0, len(self._reader(path))))
        return shards

    def read_records(self, task):
        reader = self._reader(task.shard.name)
        if task.shard.record_indices:
            # Shuffled task: the offset index gives O(1) random access, so
            # honor the master's permutation instead of the linear range.
            records = (reader.read(i) for i in task.shard.record_indices)
        else:
            records = reader.read_range(task.shard.start, task.shard.end)
        for payload in records:
            yield self._decode_fn(payload) if self._decode_fn else payload


class TextDataReader(AbstractDataReader):
    """CSV reader with fixed-size shards (reference: text_reader.py:25-72).

    Only a byte-offset index is held in memory (~8 B/line); record reads
    seek into the file, so per-process memory stays proportional to one
    task regardless of file size.
    """

    def __init__(self, filename, records_per_task=200, skip_header=False):
        self._filename = filename
        self._records_per_task = records_per_task
        self._offsets = []
        with open(filename, "rb") as f:
            if skip_header:
                f.readline()
            pos = f.tell()
            for line in f:
                self._offsets.append(pos)
                pos += len(line)
        self._f = open(filename, "rb")

    def create_shards(self):
        n = len(self._offsets)
        shards = []
        start = 0
        while start < n:
            end = min(start + self._records_per_task, n)
            shards.append((self._filename, start, end))
            start = end
        return shards

    def read_records(self, task):
        indices = task.shard.record_indices or range(
            task.shard.start, min(task.shard.end, len(self._offsets))
        )
        lines = []
        for i in indices:
            if i < len(self._offsets):
                self._f.seek(self._offsets[i])
                lines.append(self._f.readline().decode("utf-8"))
        yield from csv.reader(lines)

    def get_size(self):
        return len(self._offsets)


class ArrayDataReader(AbstractDataReader):
    """In-memory ndarray dataset; shards are index ranges.

    The natural TPU-side reader for benchmark/synthetic data: records are
    (x, y) ndarray tuples and never leave host memory until the batch is
    device_put as one contiguous block.
    """

    def __init__(self, arrays, records_per_shard=1024, name="memory"):
        self._arrays = tuple(np.asarray(a) for a in arrays)
        n = self._arrays[0].shape[0]
        if any(a.shape[0] != n for a in self._arrays):
            raise ValueError("all arrays must share dim 0")
        self._n = n
        self._records_per_shard = records_per_shard
        self._name = name

    @property
    def records_per_shard(self):
        return self._records_per_shard

    def create_shards(self):
        shards = []
        start = 0
        while start < self._n:
            end = min(start + self._records_per_shard, self._n)
            shards.append((self._name, start, end))
            start = end
        return shards

    def read_records(self, task):
        indices = task.shard.record_indices
        if indices:
            for i in indices:
                yield tuple(a[i] for a in self._arrays)
        else:
            for i in range(task.shard.start, task.shard.end):
                yield tuple(a[i] for a in self._arrays)
