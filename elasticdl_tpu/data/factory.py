"""Data reader factory (parity:
elasticdl/python/data/reader/data_reader_factory.py:23-79)."""

import os


def create_data_reader(data_origin, records_per_shard=256, **kwargs):
    if data_origin.startswith("synthetic_mnist"):
        from elasticdl_tpu.data.reader import ArrayDataReader
        from elasticdl_tpu.models import mnist

        _, _, n = data_origin.partition(":")
        xs, ys = mnist.synthetic_data(n=int(n) if n else 2048)
        return ArrayDataReader(
            (xs, ys), records_per_shard=records_per_shard
        )
    if data_origin.startswith("synthetic_cifar10"):
        from elasticdl_tpu.data.reader import ArrayDataReader
        import numpy as np

        _, _, n = data_origin.partition(":")
        n = int(n) if n else 2048
        rng = np.random.RandomState(0)
        xs = rng.rand(n, 32, 32, 3).astype(np.float32)
        ys = rng.randint(0, 10, size=n).astype(np.int32)
        return ArrayDataReader((xs, ys), records_per_shard=records_per_shard)
    if data_origin.startswith("synthetic_lm"):
        from elasticdl_tpu.data.reader import ArrayDataReader
        import numpy as np

        # "synthetic_lm[:n[:seq_len[:vocab]]]"
        parts = data_origin.split(":")
        n = int(parts[1]) if len(parts) > 1 else 2048
        seq_len = int(parts[2]) if len(parts) > 2 else 128
        vocab = int(parts[3]) if len(parts) > 3 else 1024
        rng = np.random.RandomState(0)
        # learnable structure: arithmetic token sequences mod vocab
        starts = rng.randint(0, vocab, size=n)
        steps = rng.randint(1, 7, size=n)
        toks = (
            starts[:, None] + steps[:, None] * np.arange(seq_len)[None]
        ) % vocab
        return ArrayDataReader(
            (toks.astype(np.int32),), records_per_shard=records_per_shard
        )
    if data_origin.startswith("synthetic_ctr"):
        from elasticdl_tpu.data.reader import ArrayDataReader
        from elasticdl_tpu.models import deepfm

        _, _, n = data_origin.partition(":")
        dense, ids, labels = deepfm.synthetic_data(
            n=int(n) if n else 4096
        )
        return ArrayDataReader(
            (dense, ids, labels), records_per_shard=records_per_shard
        )
    if data_origin.startswith("tokens:"):
        # "tokens:<path>:<seq_len>[:<dtype>]" — flat binary token file
        # (GPT-style pretraining data), memory-mapped windows.
        import numpy as np

        from elasticdl_tpu.data.token_reader import TokenFileDataReader

        parts = data_origin.split(":")
        if len(parts) < 3:
            raise ValueError(
                "tokens origin needs tokens:<path>:<seq_len>[:<dtype>]")
        dtype = parts[3] if len(parts) > 3 else "uint16"
        if dtype not in ("uint16", "uint32"):
            # A float or typo'd dtype would memmap the bytes as
            # garbage and train on noise with no error.
            raise ValueError(
                "tokens dtype must be uint16 or uint32, got %r"
                % dtype)
        return TokenFileDataReader(
            parts[1], seq_len=int(parts[2]), dtype=np.dtype(dtype),
            records_per_shard=records_per_shard,
        )
    if data_origin.startswith("imagefolder:"):
        # "imagefolder:<root>[:<image_size>[:augment]]" —
        # ImageNet-layout dirs; the optional literal "augment" enables
        # training-time random crop + horizontal flip.
        from elasticdl_tpu.data.image_folder import ImageFolderDataReader

        parts = data_origin.split(":")
        root = parts[1]
        image_size = int(parts[2]) if len(parts) > 2 else 224
        augment = len(parts) > 3 and parts[3] == "augment"
        if (len(parts) > 3 and not augment) or len(parts) > 4:
            raise ValueError(
                "imagefolder options %r not understood (only a "
                "single 'augment')" % (parts[3:],))
        return ImageFolderDataReader(
            root, image_size=image_size,
            records_per_shard=records_per_shard, augment=augment,
        )
    if data_origin.endswith(".csv"):
        from elasticdl_tpu.data.reader import TextDataReader

        return TextDataReader(
            data_origin, records_per_task=records_per_shard,
            skip_header=kwargs.get("skip_header", False),
        )
    if os.path.isdir(data_origin):
        from elasticdl_tpu.data.reader import RecioDataReader
        from elasticdl_tpu.data.recio_gen import decode_xy

        return RecioDataReader(
            data_origin, decode_fn=kwargs.get("decode_fn", decode_xy)
        )
    if data_origin.endswith(".db") or data_origin.startswith("sql:"):
        from elasticdl_tpu.data.sql_reader import SQLTableDataReader

        spec = data_origin[4:] if data_origin.startswith("sql:") \
            else data_origin
        database, _, table = spec.partition("#")
        return SQLTableDataReader(
            database, table or "samples",
            records_per_shard=records_per_shard,
        )
    raise ValueError("cannot infer a data reader for %r" % data_origin)
