"""Kubernetes resource-string parsing for cluster submission.

(Manifest building lives in ``client/k8s_submit.py`` — dict manifests
shared by the API-submission and ``--output`` rendering paths.  The YAML
string template that used to live here was superseded by it and removed,
VERDICT r3 #8.)
"""


def parse_volume_string(spec):
    """'claim_name=c1,mount_path=/p1;host_path=/d,mount_path=/p2' ->
    (volumes, volume_mounts) dict manifests.

    Reference semantics (elasticdl_client/common/k8s_volume.py):
    ``;``-separated volume entries of ``,``-separated k=v pairs; a
    ``claim_name`` entry mounts a PersistentVolumeClaim, a
    ``host_path`` entry mounts a host directory; ``mount_path`` is
    required, ``sub_path`` and ``read_only`` optional.  Repeating the
    same claim/host path reuses ONE volume with multiple mounts.
    """
    volumes = []
    mounts = []
    seen = {}  # (type, source) -> volume name

    def _volume_name(kind, source):
        key = (kind, source)
        if key not in seen:
            import zlib

            slug = "".join(
                ch if ch.isalnum() else "-" for ch in source
            ).strip("-").lower() or "root"
            # Distinct sources can collapse to one slug ('data.x' and
            # 'data-x' both -> 'data-x'); a source hash keeps the k8s
            # volume names unique (and the truncation 63-char-safe).
            seen[key] = "%s-%s-%04x" % (
                kind, slug[:40], zlib.crc32(source.encode()) & 0xFFFF)
        return seen[key]

    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        fields = {}
        for piece in entry.split(","):
            key, sep, value = piece.strip().partition("=")
            if not sep:
                raise ValueError("bad volume entry %r" % piece)
            fields[key.strip()] = value.strip()
        if "mount_path" not in fields:
            raise ValueError("volume entry %r needs mount_path" % entry)
        if "claim_name" in fields:
            name = _volume_name("pvc", fields["claim_name"])
            volume = {
                "name": name,
                "persistentVolumeClaim": {
                    "claimName": fields["claim_name"],
                    "readOnly": False,
                },
            }
        elif "host_path" in fields:
            name = _volume_name("hostpath", fields["host_path"])
            volume = {
                "name": name,
                "hostPath": {"path": fields["host_path"]},
            }
        else:
            raise ValueError(
                "volume entry %r needs claim_name or host_path" % entry)
        if all(v["name"] != name for v in volumes):
            volumes.append(volume)
        mount = {"name": name, "mountPath": fields["mount_path"]}
        if fields.get("sub_path"):
            mount["subPath"] = fields["sub_path"]
        if fields.get("read_only", "").lower() in ("true", "1", "yes"):
            mount["readOnly"] = True
        mounts.append(mount)
    return volumes, mounts


def parse_resource_string(spec):
    """'cpu=1,memory=4096Mi,google.com/tpu=8' -> k8s resource dict
    (reference: elasticdl_client/common/k8s_resource.py)."""
    out = {}
    for piece in spec.split(","):
        piece = piece.strip()
        if not piece:
            continue
        key, sep, value = piece.partition("=")
        if not sep:
            raise ValueError("bad resource entry %r" % piece)
        out[key.strip()] = value.strip()
    return out
