"""Kubernetes manifest rendering for cluster submission.

The reference submits jobs by creating a master pod through the k8s API
(elasticdl_client/api.py:199-256, common/k8s_client.py:220-410) with labels
``elasticdl-job-name`` / ``replica-type`` / ``replica-index``.  This image
has no cluster, so the client renders equivalent manifests for kubectl;
the label scheme and master-owns-workers ownership model are preserved
(workers/PS are created by the master at runtime via its worker-manager
backend, exactly like the reference's pod manager).
"""

import shlex


def parse_resource_string(spec):
    """'cpu=1,memory=4096Mi,google.com/tpu=8' -> k8s resource dict
    (reference: elasticdl_client/common/k8s_resource.py)."""
    out = {}
    for piece in spec.split(","):
        piece = piece.strip()
        if not piece:
            continue
        key, sep, value = piece.partition("=")
        if not sep:
            raise ValueError("bad resource entry %r" % piece)
        out[key.strip()] = value.strip()
    return out

_MASTER_POD_TEMPLATE = """apiVersion: v1
kind: Pod
metadata:
  name: {job_name}-master
  namespace: {namespace}
  labels:
    elasticdl-tpu-job-name: {job_name}
    replica-type: master
    replica-index: "0"
spec:
  restartPolicy: Never
  containers:
  - name: master
    image: {image}
    command: ["python", "-m", "elasticdl_tpu.master.main"]
    args: [{args}]
    env:
    - name: JOB_NAME
      value: {job_name}
    resources:
      requests:
        cpu: "1"
        memory: 2Gi
---
apiVersion: v1
kind: Service
metadata:
  name: {job_name}-master
  namespace: {namespace}
spec:
  selector:
    elasticdl-tpu-job-name: {job_name}
    replica-type: master
  ports:
  - port: 50001
    targetPort: 50001
"""


def render_master_manifest(master_argv, image, namespace="default",
                           job_name=None):
    if job_name is None:
        job_name = "elasticdl-tpu-job"
        if "--job_name" in master_argv:
            job_name = master_argv[
                master_argv.index("--job_name") + 1
            ]
    args = ", ".join(
        '"%s"' % shlex.quote(str(a)) for a in master_argv
    )
    return _MASTER_POD_TEMPLATE.format(
        job_name=job_name, namespace=namespace, image=image, args=args
    )
