"""Kubernetes resource-string parsing for cluster submission.

(Manifest building lives in ``client/k8s_submit.py`` — dict manifests
shared by the API-submission and ``--output`` rendering paths.  The YAML
string template that used to live here was superseded by it and removed,
VERDICT r3 #8.)
"""


def parse_resource_string(spec):
    """'cpu=1,memory=4096Mi,google.com/tpu=8' -> k8s resource dict
    (reference: elasticdl_client/common/k8s_resource.py)."""
    out = {}
    for piece in spec.split(","):
        piece = piece.strip()
        if not piece:
            continue
        key, sep, value = piece.partition("=")
        if not sep:
            raise ValueError("bad resource entry %r" % piece)
        out[key.strip()] = value.strip()
    return out
