"""The ``elasticdl-tpu`` CLI (parity: elasticdl_client/main.py:28-103).

Subcommands:
  zoo init | zoo build | zoo push     — model-zoo project tooling
  serve                               — HTTP model server over a
                                      servable export (serving/server)
  train | evaluate | predict          — submit a job:
      --platform local  (default)     run the master (and its managed
                                      worker/PS processes) on this host
      --platform k8s                  CREATE the master pod + service on
                                      the cluster via the k8s API
                                      (reference elasticdl_client/
                                      api.py:199-256); pass --output
                                      PATH|- to render the manifests for
                                      kubectl instead of submitting
"""

import argparse
import os
import subprocess
import sys

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _add_job_args(parser):
    parser.add_argument("--platform", default="local",
                        choices=["local", "k8s"])
    parser.add_argument("--image", default="elasticdl-tpu:latest",
                        help="container image (k8s platform)")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--output", default=None,
                        help="instead of creating the master pod via the "
                             "k8s API, render its manifests to this path "
                             "('-' = stdout) for kubectl")
    parser.add_argument("--master_resource_request",
                        default="cpu=1,memory=2Gi",
                        help="k8s resources for the master pod, "
                             "'cpu=1,memory=2Gi,...'")
    parser.add_argument("--cluster_spec", default="",
                        help="dotted module with patch_pod/patch_service "
                             "hooks applied to every manifest")


def _split_args(argv):
    """Separate CLI-level args from master-level passthrough args."""
    cli = argparse.ArgumentParser("elasticdl-tpu job")
    _add_job_args(cli)
    cli_args, rest = cli.parse_known_args(argv)
    return cli_args, rest


def _run_job(job_type, argv, core_api=None):
    cli_args, master_argv = _split_args(argv)
    master_argv = ["--job_type", job_type] + master_argv
    if cli_args.platform == "local":
        from elasticdl_tpu.master.main import main as master_main

        return master_main(master_argv)
    from elasticdl_tpu.client import k8s_submit
    from elasticdl_tpu.client.k8s_renderer import parse_resource_string

    # parse_known_args consumed --namespace/--image/--cluster_spec above,
    # but the IN-CLUSTER master needs them too (worker pods in the same
    # namespace/image, same patch hooks) — forward the parsed values.
    master_argv = master_argv + [
        "--namespace", cli_args.namespace,
        "--image", cli_args.image,
        "--cluster_spec", cli_args.cluster_spec,
    ]
    if not any(a == "--worker_backend" or a.startswith("--worker_backend=")
               for a in master_argv):
        # A cluster submission wants worker PODS; without this the
        # in-cluster master would run workers as subprocesses inside its
        # own cpu=1 pod (worker_backend defaults to "process").  An
        # explicit --worker_backend in the job args still wins.
        master_argv += ["--worker_backend", "k8s"]
    resources = parse_resource_string(cli_args.master_resource_request)
    if cli_args.output is not None:
        manifest = k8s_submit.render_manifests(
            master_argv, image=cli_args.image,
            namespace=cli_args.namespace, resources=resources,
            cluster_spec=cli_args.cluster_spec,
        )
        if cli_args.output == "-":
            print(manifest)
        else:
            with open(cli_args.output, "w") as f:
                f.write(manifest)
            logger.info("wrote manifest to %s", cli_args.output)
        return 0
    k8s_submit.submit_job(
        master_argv, image=cli_args.image, namespace=cli_args.namespace,
        resources=resources, cluster_spec=cli_args.cluster_spec,
        core_api=core_api,
    )
    return 0


# -- zoo tooling --------------------------------------------------------------

_ZOO_TEMPLATE = '''"""Model zoo module — exports model_spec(**kwargs)."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.utils import metrics


class Model(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(2)(x)


def feed(records):
    xs = np.stack([np.asarray(r[0], np.float32) for r in records])
    ys = np.asarray([int(r[1]) for r in records], np.int32)
    return xs, ys


def model_spec(learning_rate=1e-3):
    model = Model()
    return ModelSpec(
        name="my_model",
        init_fn=lambda rng: model.init(rng, jnp.zeros((1, 8)))["params"],
        apply_fn=lambda p, x, t: model.apply({"params": p}, x, train=t),
        loss_fn=lambda logits, labels:
            optax.softmax_cross_entropy_with_integer_labels(logits,
                                                            labels),
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {"accuracy": metrics.Accuracy()},
    )
'''

_DOCKERFILE_TEMPLATE = """# Generated by `elasticdl-tpu zoo init`
FROM python:3.12-slim
RUN pip install jax[tpu] flax optax grpcio protobuf numpy
COPY . /model_zoo
ENV PYTHONPATH=/model_zoo
"""


def _zoo_init(args):
    os.makedirs(args.path, exist_ok=True)
    model_file = os.path.join(args.path, "my_model.py")
    if not os.path.exists(model_file):
        with open(model_file, "w") as f:
            f.write(_ZOO_TEMPLATE)
    with open(os.path.join(args.path, "Dockerfile"), "w") as f:
        f.write(_DOCKERFILE_TEMPLATE)
    logger.info("initialized model zoo at %s", args.path)
    return 0


def _zoo_build(args):
    cmd = ["docker", "build", "-t", args.image, args.path]
    logger.info("running: %s", " ".join(cmd))
    return subprocess.call(cmd)


def _zoo_push(args):
    cmd = ["docker", "push", args.image]
    logger.info("running: %s", " ".join(cmd))
    return subprocess.call(cmd)


def _inspect(args):
    """Operator tooling: summarize an export or checkpoint directory.

    Detects servable exports (manifest.json, incl. versioned
    ``<base>/<N>/`` layouts — inspects the latest) and checkpoint dirs
    (``version-*``) and prints params/tables/sizes so operators don't
    spelunk npz files by hand.
    """
    import numpy as np

    path = args.path

    def _fmt_bytes(n):
        for unit in ("B", "KB", "MB", "GB"):
            if n < 1024 or unit == "GB":
                return "%.1f %s" % (n, unit)
            n /= 1024.0

    from elasticdl_tpu.serving.loader import resolve_export_dir

    versions = sorted(
        int(e) for e in os.listdir(path)
        if e.isdigit() and os.path.isfile(
            os.path.join(path, e, "manifest.json"))
    ) if os.path.isdir(path) else []  # display only; resolution below
    try:
        target = resolve_export_dir(path)  # the ONE canonical scan
    except (FileNotFoundError, NotADirectoryError):
        target = path
    if os.path.isfile(os.path.join(target, "manifest.json")):
        import json as _json

        with open(os.path.join(target, "manifest.json")) as f:
            manifest = _json.load(f)
        print("servable export: %s" % target)
        if versions:
            print("  versions on disk: %s (latest shown)" % versions)
        for key in ("format", "model_name", "version",
                    "polymorphic_batch", "platforms"):
            print("  %s: %s" % (key, manifest.get(key)))
        quantized = manifest.get("quantized_int8") or []
        if quantized:
            print("  int8-quantized: %s" % ", ".join(quantized))
        npz_path = os.path.join(target, "model.npz")
        # Header-only scan: shapes/dtypes come from each member's npy
        # header, so inspecting a multi-GB export never materializes
        # an array.  int8-quantized entries count at float32 size in
        # the in-memory figure (both loaders dequantize at load).
        import zipfile

        total = 0
        n_params = 0
        tables = {}
        with zipfile.ZipFile(npz_path) as zf:
            for info in zf.infolist():
                key = info.filename[:-4]  # strip ".npy"
                with zf.open(info) as member:
                    np.lib.format.read_magic(member)
                    shape, _f, dtype = (
                        np.lib.format.read_array_header_1_0(member))
                nbytes = int(np.prod(shape)) * dtype.itemsize
                if key.startswith(("q8/", "q8emb/")):
                    nbytes *= 4  # dequantized to float32 in memory
                total += nbytes
                if key.startswith("emb_ids/"):
                    tables[key[len("emb_ids/"):]] = int(shape[0])
                elif not key.startswith(
                    ("emb_vals/", "q8emb/", "q8embscale/", "q8scale/")
                ):
                    n_params += 1
        print("  parameters: %d arrays, weights file %s on disk"
              % (n_params, _fmt_bytes(os.path.getsize(npz_path))))
        print("  in-memory (dequantized): %s" % _fmt_bytes(total))
        for name, rows in sorted(tables.items()):
            print("  table %s: %d rows" % (name, rows))
        return 0

    from elasticdl_tpu.utils.checkpoint import CheckpointSaver

    entries = sorted(
        e for e in os.listdir(path) if e.startswith("version-")
    ) if os.path.isdir(path) else []
    if not entries:
        print("nothing to inspect at %s (no manifest.json, no "
              "version-* checkpoints)" % path)
        return 1
    print("checkpoint dir: %s" % path)
    for entry in entries:
        vdir = os.path.join(path, entry)
        shards = sorted(os.listdir(vdir))
        size = sum(
            os.path.getsize(os.path.join(vdir, s)) for s in shards
        )
        print("  %s: %d shard file(s), %s"
              % (entry, len(shards), _fmt_bytes(size)))
    saver = CheckpointSaver(path)
    try:
        dense, embeddings, version = saver.load()
        n_opt = sum(1 for k in dense if k.startswith("opt/")
                    or k.startswith("optslot/"))
        print("  latest loadable: version %d — %d dense arrays "
              "(%d optimizer), %d embedding tables"
              % (version, len(dense), n_opt, len(embeddings)))
    except Exception as e:  # noqa: BLE001 — partial/corrupt dirs
        print("  latest not loadable: %s" % e)
    return 0


def build_parser():
    parser = argparse.ArgumentParser(
        "elasticdl-tpu",
        description="TPU-native elastic deep learning CLI",
    )
    sub = parser.add_subparsers(dest="command")

    zoo = sub.add_parser("zoo", help="model zoo tooling")
    zoo_sub = zoo.add_subparsers(dest="zoo_command")
    p = zoo_sub.add_parser("init")
    p.add_argument("path", nargs="?", default=".")
    p = zoo_sub.add_parser("build")
    p.add_argument("path", nargs="?", default=".")
    p.add_argument("--image", required=True)
    p = zoo_sub.add_parser("push")
    p.add_argument("--image", required=True)

    for job in ("train", "evaluate", "predict"):
        p = sub.add_parser(
            job, add_help=False,
            help="%s job (plus all master flags)" % job,
        )
        _add_job_args(p)
    sub.add_parser(
        "serve", add_help=False,
        help="serve a servable export over HTTP "
             "(--export_dir DIR [--port P] [--model_name N])",
    )
    p = sub.add_parser(
        "inspect", help="summarize an export or checkpoint directory")
    p.add_argument("path")
    return parser


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = build_parser()
    if not argv:
        parser.print_help()
        return 1
    command = argv[0]
    if command in ("train", "evaluate", "predict"):
        return _run_job(command, argv[1:])
    if command == "serve":
        from elasticdl_tpu.serving.server import main as serve_main

        return serve_main(argv[1:])
    args = parser.parse_args(argv)
    if args.command == "inspect":
        return _inspect(args)
    if args.command == "zoo":
        if args.zoo_command == "init":
            return _zoo_init(args)
        if args.zoo_command == "build":
            return _zoo_build(args)
        if args.zoo_command == "push":
            return _zoo_push(args)
    parser.print_help()
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
