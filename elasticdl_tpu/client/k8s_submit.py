"""Create the master pod on a Kubernetes cluster (real submission path).

Parity: the reference CLI does not just render YAML — it re-serializes
the parsed args into a container command line and CREATES the master pod
through the k8s API (elasticdl_client/api.py:199-256,
common/k8s_client.py:220-357).  This module is that path for
elasticdl-tpu: dict manifests (the k8s API accepts them directly),
created via an injectable CoreV1Api so the whole flow unit-tests against
a fake API with no ``kubernetes`` package in the image.

The master pod gets the reference's label scheme and downward-API env
(POD_NAME / POD_UID), so the in-cluster master can stamp itself as the
ownerReference on every worker pod it creates — deleting the master
cascades the whole job, the reference's ownership model
(common/k8s_client.py:354-357).

Manifest rendering (``--output``) stays available for kubectl-driven
submission; both paths build the same dicts.
"""

import json

from elasticdl_tpu.master.k8s_backend import (
    LABEL_INDEX,
    LABEL_JOB,
    LABEL_TYPE,
    apply_spec_hook,
    default_core_api,
    load_cluster_spec,
)
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

MASTER_PORT = 50001


def _argv_value(master_argv, flag, default=None):
    for i, arg in enumerate(master_argv):
        if arg == flag and i + 1 < len(master_argv):
            return master_argv[i + 1]
        if arg.startswith(flag + "="):
            return arg.split("=", 1)[1]
    return default


def _job_name_from_argv(master_argv):
    return _argv_value(master_argv, "--job_name", "elasticdl-tpu-job")


def _port_from_argv(master_argv):
    """The port the in-cluster master will bind (Service must match).

    An explicit ``--port`` in the job args parameterizes the Service
    port/targetPort; otherwise the master falls back to MASTER_PORT
    (master/main.py) and so does the Service.
    """
    port = _argv_value(master_argv, "--port")
    try:
        port = int(port) if port else 0
    except ValueError:
        raise ValueError(
            "--port must be an integer, got %r" % port) from None
    return port or MASTER_PORT


def master_pod_name(job_name):
    return "%s-master" % job_name


def master_pod_manifest(master_argv, image, namespace="default",
                        job_name=None, resources=None, envs=None):
    """The master pod as a dict manifest.

    ``resources``: k8s resource-request dict (see
    k8s_renderer.parse_resource_string).  ``envs``: extra {name: value}
    pairs for the master container.
    """
    job_name = job_name or _job_name_from_argv(master_argv)
    env = [
        {"name": "JOB_NAME", "value": job_name},
        # Downward API: the master learns its own pod identity so it can
        # set itself as ownerReference on the workers it creates.
        {"name": "POD_NAME", "fieldRef": {"fieldPath": "metadata.name"}},
        {"name": "POD_UID", "fieldRef": {"fieldPath": "metadata.uid"}},
        {"name": "POD_NAMESPACE",
         "fieldRef": {"fieldPath": "metadata.namespace"}},
        # Pod IP: the per-epoch coordination services bind fresh ports
        # that the master's Service does not map — workers dial the
        # master POD directly for those (master/main.py coord_host).
        {"name": "POD_IP", "fieldRef": {"fieldPath": "status.podIP"}},
    ]
    env = [
        e if "fieldRef" not in e else
        {"name": e["name"], "valueFrom": {"fieldRef": e["fieldRef"]}}
        for e in env
    ]
    for name, value in (envs or {}).items():
        env.append({"name": name, "value": str(value)})
    # --volume in the job args mounts on the master pod too (the worker
    # pods get the same mounts from K8sWorkerBackend) — reference
    # k8s_volume.py semantics.
    from elasticdl_tpu.client.k8s_renderer import parse_volume_string

    volumes, mounts = parse_volume_string(
        _argv_value(master_argv, "--volume", ""))
    manifest = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": master_pod_name(job_name),
            "namespace": namespace,
            "labels": {
                LABEL_JOB: job_name,
                LABEL_TYPE: "master",
                LABEL_INDEX: "0",
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [{
                "name": "master",
                "image": image,
                "command": ["python", "-m", "elasticdl_tpu.master.main"],
                "args": [str(a) for a in master_argv],
                "env": env,
                "resources": {
                    "requests": dict(
                        resources or {"cpu": "1", "memory": "2Gi"}
                    )
                },
            }],
        },
    }
    if volumes:
        manifest["spec"]["volumes"] = volumes
        manifest["spec"]["containers"][0]["volumeMounts"] = mounts
    return manifest


def master_service_manifest(job_name, namespace="default",
                            port=MASTER_PORT):
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": master_pod_name(job_name),
            "namespace": namespace,
            "labels": {
                LABEL_JOB: job_name,
                LABEL_TYPE: "master",
                LABEL_INDEX: "0",
            },
        },
        "spec": {
            "selector": {
                LABEL_JOB: job_name,
                LABEL_TYPE: "master",
            },
            "ports": [{"port": port, "targetPort": port}],
        },
    }


def render_manifests(master_argv, image, namespace="default",
                     job_name=None, resources=None, envs=None,
                     cluster_spec=""):
    """Multi-doc YAML for kubectl (JSON docs — JSON is valid YAML)."""
    pod, svc = build_manifests(
        master_argv, image, namespace=namespace, job_name=job_name,
        resources=resources, envs=envs, cluster_spec=cluster_spec,
    )
    return "---\n".join(
        json.dumps(doc, indent=2) + "\n" for doc in (pod, svc)
    )


def build_manifests(master_argv, image, namespace="default",
                    job_name=None, resources=None, envs=None,
                    cluster_spec=""):
    spec_mod = (
        load_cluster_spec(cluster_spec)
        if isinstance(cluster_spec, str) else cluster_spec
    )
    job_name = job_name or _job_name_from_argv(master_argv)
    pod = master_pod_manifest(
        master_argv, image, namespace=namespace, job_name=job_name,
        resources=resources, envs=envs,
    )
    svc = master_service_manifest(
        job_name, namespace=namespace, port=_port_from_argv(master_argv)
    )
    return (
        apply_spec_hook(spec_mod, pod, "patch_pod"),
        apply_spec_hook(spec_mod, svc, "patch_service"),
    )


def submit_job(master_argv, image, namespace="default", job_name=None,
               resources=None, envs=None, cluster_spec="",
               core_api=None):
    """Create the master pod + service; returns the master pod name.

    ``core_api`` is injectable (tests use a fake); the default imports
    the real kubernetes client and loads kubeconfig credentials.
    """
    if core_api is None:
        core_api = default_core_api()
    pod, svc = build_manifests(
        master_argv, image, namespace=namespace, job_name=job_name,
        resources=resources, envs=envs, cluster_spec=cluster_spec,
    )
    core_api.create_namespaced_pod(namespace, pod)
    core_api.create_namespaced_service(namespace, svc)
    name = pod["metadata"]["name"]
    logger.info("submitted master pod %s (namespace %s)", name, namespace)
    return name
