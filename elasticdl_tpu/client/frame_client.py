"""Frame client SDK — the one consumer-side home for the binary wire.

Every surface that speaks the frame protocol (docs/serving.md "Wire
protocol") is served by this one client: serving ``:predict`` and
``:lookup`` (directly or through the router — the router forwards
frame bodies byte-identically), and the aggregation tier's streamed
``POST /ingest``.  Before this module each consumer — the serving
bench, the router passthrough check, ad-hoc scripts — carried its own
``http.client`` + codec dance; now they share one encode/decode path,
one keep-alive pooling discipline, and one error-surfacing contract:

 - a 400 reply (the server's codec refused the frame, or ours refused
   the reply) raises :class:`~elasticdl_tpu.utils.tensor_codec.
   FrameError` — the SAME exception the codec raises locally, so a
   caller's malformed-frame handling is transport-blind;
 - ingest's version-monotone refusal (409) raises
   :class:`StaleVersionError` and its program-cache miss (422) raises
   :class:`ProgramRequiredError` — distinct types because the caller's
   recovery differs (skip vs re-send with the program in-band);
 - anything else raises :class:`FrameClientError` with the status and
   the server's error body.

Keep-alive pooling: connections are CHECKED OUT for the round-trip and
checked back in after — never held under a lock across IO (the repo's
lock discipline, enforced by elastic-lint EL006; this client's own
socket-touching methods are registered in the blocking registry so a
CALLER holding a lock across ``predict``/``lookup``/``ingest`` gets
flagged too).  A pooled connection the server idled out is retried
once on a fresh one — the standard keep-alive race.

One client is thread-safe; per-thread clients avoid pool contention in
tight benchmark loops.
"""

import http.client
import json
import threading

import numpy as np

from elasticdl_tpu.utils import tensor_codec
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.tensor_codec import FrameError

logger = get_logger(__name__)


class FrameClientError(RuntimeError):
    """A non-200 reply from a frame endpoint: carries the HTTP
    ``status`` and the server's error ``message``."""

    def __init__(self, status, message):
        super().__init__("HTTP %d: %s" % (status, message))
        self.status = status
        self.message = message


class StaleVersionError(FrameClientError):
    """Ingest 409: the receiver already ingested this version or a
    newer one (version-monotone stream) — skip, don't retry."""


class ProgramRequiredError(FrameClientError):
    """Ingest 422: the frame's parameter tree is new to the receiver
    and no StableHLO program rode along (a restarted aggregator lost
    its cache) — re-send with ``include_program=True``."""


def encode_predict(inputs, wire_dtype=None, response_wire=None,
                   routing_key=None):
    """Encode a ``:predict`` request frame: an array becomes the
    single ``instances`` tensor (array-input models), a dict one named
    tensor per input leaf.  ``wire_dtype`` compresses the REQUEST
    payload; ``response_wire`` asks the server to compress the reply;
    ``routing_key`` pins the request to a canary cohort slice."""
    if isinstance(inputs, dict):
        tensors = {k: np.asarray(v) for k, v in inputs.items()}
    else:
        tensors = {"instances": np.asarray(inputs)}
    meta = {"response_wire": response_wire} if response_wire else None
    return tensor_codec.encode_frame(
        tensors, kind="predict", wire_dtype=wire_dtype, meta=meta,
        routing_key=routing_key)


def decode_predictions(frame):
    """A ``predictions`` reply frame -> the model's output pytree
    (the flattened tensors reassembled through the tree spec the
    server put in meta)."""
    if frame.kind != "predictions":
        raise FrameError("not a predictions frame (kind %r)"
                         % frame.kind)
    return tensor_codec.unflatten_tree(frame.meta.get("tree"),
                                       frame.tensors)


class FrameClient:
    """One frame-speaking peer (serving replica, router, or
    aggregator ingest endpoint) at ``addr`` ("host:port")."""

    def __init__(self, addr, timeout=30.0, pool_size=8):
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError("addr must be host:port, got %r"
                             % (addr,))
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self._pool_lock = threading.Lock()
        self._pool = []
        self._pool_size = int(pool_size)

    # -- connection pooling --------------------------------------------

    def _connect(self):
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    def _checkout(self):
        """(connection, reused): a parked keep-alive connection when
        one is available, else a fresh dial."""
        with self._pool_lock:
            if self._pool:
                return self._pool.pop(), True
        return self._connect(), False

    def _checkin(self, conn):
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(conn)
                return
        conn.close()

    def close(self):
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()

    # -- transport -----------------------------------------------------

    def roundtrip(self, path, body,
                  content_type=tensor_codec.FRAME_CONTENT_TYPE):
        """POST ``body`` to ``path`` over a pooled connection; returns
        (status, reply content type, reply bytes).  The low-level
        surface for byte-level consumers (the router-passthrough
        identity check); typed callers use predict/lookup/ingest.  A
        REUSED connection that fails before a reply is retried once on
        a fresh dial — the server idling out a parked connection must
        not surface as a request failure."""
        conn, reused = self._checkout()
        headers = {"Content-Type": content_type}
        for attempt in (0, 1):
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            except (OSError, http.client.HTTPException):
                conn.close()
                if not reused or attempt:
                    raise
                conn, reused = self._connect(), False
                continue
            if resp.getheader("Connection", "").lower() == "close":
                conn.close()  # a draining replica said goodbye
            else:
                self._checkin(conn)
            return resp.status, resp.getheader("Content-Type") or "", \
                raw
        raise AssertionError("unreachable")

    @staticmethod
    def _error(status, raw):
        """Map an error reply to the surfaced exception type."""
        try:
            body = json.loads(raw.decode() or "{}")
            message = body.get("error") or json.dumps(body)
        except (ValueError, UnicodeDecodeError):
            message = repr(raw[:200])
        if status == 400:
            # The peer's codec refused the frame: surface it as the
            # SAME exception a local decode raises.
            return FrameError(message)
        if status == 409:
            return StaleVersionError(status, message)
        if status == 422:
            return ProgramRequiredError(status, message)
        return FrameClientError(status, message)

    def _frame_call(self, path, blob):
        status, ctype, raw = self.roundtrip(path, blob)
        if status != 200:
            raise self._error(status, raw)
        if not tensor_codec.is_frame_content_type(ctype):
            raise FrameClientError(
                status, "expected a frame reply, got %r" % (ctype,))
        return tensor_codec.decode_frame(raw)

    # -- serving data plane --------------------------------------------

    def predict_frame(self, model, blob):
        """POST a pre-encoded ``:predict`` frame; returns the decoded
        reply :class:`~elasticdl_tpu.utils.tensor_codec.Frame`.  The
        encode-once/replay surface (benchmarks, replayed corpora);
        :meth:`predict` is the typed wrapper."""
        return self._frame_call("/v1/models/%s:predict" % model, blob)

    def predict(self, model, inputs, wire_dtype=None,
                response_wire=None, routing_key=None):
        """One prediction round-trip: pytree of inputs in, the model's
        output pytree back (typed ndarrays, no JSON row lists)."""
        frame = self.predict_frame(
            model, encode_predict(inputs, wire_dtype=wire_dtype,
                                  response_wire=response_wire,
                                  routing_key=routing_key))
        return decode_predictions(frame)

    def lookup(self, model, table, ids, source=None,
               response_wire=None):
        """Embedding lookup: int64 ids in, ``[n, dim]`` float32 rows
        back in input order.  ``source="ps"`` forces the PS-backed
        live-table path on a replica that serves both."""
        meta = {"table": table}
        if source:
            meta["source"] = source
        if response_wire:
            meta["response_wire"] = response_wire
        blob = tensor_codec.encode_frame(
            {"ids": np.asarray(ids, np.int64)}, kind="lookup",
            meta=meta)
        frame = self._frame_call("/v1/models/%s:lookup" % model, blob)
        vectors = frame.tensors.get("vectors")
        if vectors is None:
            raise FrameError("lookup reply carries no 'vectors' "
                             "tensor")
        return vectors

    # -- aggregation ingest --------------------------------------------

    def ingest(self, blob):
        """Stream one servable frame (``ContinuousExporter.
        frame_bytes`` / ``servable_frame_bytes``) into an aggregator's
        ``POST /ingest``; returns the ingested version.  Raises
        :class:`StaleVersionError` (409), :class:`ProgramRequiredError`
        (422), or :class:`FrameError` (400) per the endpoint's status
        contract (docs/serving.md "Streamed ingest")."""
        status, _ctype, raw = self.roundtrip("/ingest", blob)
        if status != 200:
            raise self._error(status, raw)
        try:
            return int(json.loads(raw.decode()).get("ingested", 0))
        except (ValueError, UnicodeDecodeError, AttributeError):
            raise FrameClientError(
                status, "malformed ingest reply %r" % raw[:100])
