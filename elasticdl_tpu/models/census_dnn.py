"""Census DNN — model_zoo/census_dnn_model parity.

The reference ships the same census DNN three ways (functional /
sequential / subclass Keras, model_zoo/census_dnn_model/
census_functional_api.py etc.) over a shared feature-column set
(census_feature_columns.py:18-54): 4 numeric features plus 8
categorical features hashed into 64 buckets each and embedded at
dim 16.  In JAX there is one way to write a pure function, so the
three variants collapse into this module; the feature-column set is
kept behaviorally identical and compiled with the declarative
feature-column library (preprocessing/feature_column.py) so all 8
categorical features share ONE offset id space and one PS-served
embedding table.

Records are dicts (column name -> raw value), the natural row shape of
the SQL reader and of CSV-with-header sources.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models import mlp
from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.preprocessing import feature_column as fc
from elasticdl_tpu.utils import metrics

EMB_TABLE = "census_dnn_embedding"

# census_feature_columns.py:18-33 — the reference's split of the census
# schema into categorical (hash 64 -> embed 16) and numeric keys.
CATEGORICAL_KEYS = [
    "workclass", "education", "marital_status", "occupation",
    "relationship", "race", "sex", "native_country",
]
NUMERIC_KEYS = ["age", "capital_gain", "capital_loss", "hours_per_week"]
HASH_BUCKETS = 64
EMBEDDING_DIM = 16


def build_columns(use_stats=False):
    """Numeric columns (analyzer-standardized when stats are exported)
    plus one concatenated categorical column over all hash spaces."""
    if use_stats:
        numeric = [fc.NumericColumn.from_stats(k) for k in NUMERIC_KEYS]
    else:
        numeric = [fc.NumericColumn(k) for k in NUMERIC_KEYS]
    cat = fc.concatenated_categorical_column(
        [fc.CategoricalHashColumn(k, HASH_BUCKETS)
         for k in CATEGORICAL_KEYS]
    )
    return numeric, cat


def init_params(rng, num_dense, num_fields, embedding_dim,
                hidden=(64, 32)):
    sizes = [num_fields * embedding_dim + num_dense] + list(hidden) + [1]
    return mlp.mlp_init(rng, sizes)


def forward(params, feats, train):
    emb = feats["emb__" + EMB_TABLE][feats["idx__" + EMB_TABLE]]
    x = emb.reshape(emb.shape[0], -1)
    x = jnp.concatenate([x, feats["dense"]], axis=-1)
    return mlp.mlp_apply(params, x)[:, 0]


def model_spec(embedding_dim=EMBEDDING_DIM, hidden=(64, 32),
               learning_rate=1e-3, use_stats=False, column_order=""):
    """``column_order``: comma-separated column names for list-shaped
    rows (SQL/CSV sources); empty for dict-shaped records."""
    numeric, cat = build_columns(use_stats=use_stats)
    order = [c for c in column_order.split(",") if c] or None
    feed = fc.make_feed(numeric, {EMB_TABLE: cat}, column_order=order)
    num_fields = len(CATEGORICAL_KEYS)

    def init_fn(rng):
        return init_params(rng, len(numeric), num_fields, embedding_dim,
                           hidden)

    def loss_fn(logits, labels):
        return optax.sigmoid_binary_cross_entropy(
            logits, labels.astype(jnp.float32)
        )

    return ModelSpec(
        name="census_dnn",
        init_fn=init_fn,
        apply_fn=forward,
        loss_fn=loss_fn,
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {
            "auc": metrics.AUC(),
            "accuracy": metrics.BinaryAccuracy(threshold=0.0),
        },
        ps_embedding_infos=[
            {"name": EMB_TABLE, "dim": embedding_dim,
             "initializer": "uniform"},
        ],
        ps_optimizer=("adam", "learning_rate=%g" % learning_rate),
    )


def synthetic_census_records(n=1024, seed=0):
    """Dict-shaped census-like records with a learnable label rule."""
    rng = np.random.RandomState(seed)
    records = []
    for _ in range(n):
        age = int(rng.randint(17, 80))
        edu = ["hs", "college", "masters", "phd", "other"][
            rng.randint(5)]
        hours = int(rng.randint(10, 80))
        gain = int(rng.choice([0, 0, 0, 5000, 7000, 9000]))
        marital = ["single", "married", "divorced"][rng.randint(3)]
        score = (
            (age > 35) + (edu in ("masters", "phd")) * 2
            + (hours > 45) + (gain > 0) + (marital == "married")
        )
        records.append({
            "age": age,
            "workclass": ["private", "gov", "self", "none"][
                rng.randint(4)],
            "education": edu,
            "marital_status": marital,
            "occupation": "occ%d" % rng.randint(12),
            "relationship": ["own", "spouse", "child"][rng.randint(3)],
            "race": "race%d" % rng.randint(4),
            "sex": ["m", "f"][rng.randint(2)],
            "native_country": "c%d" % rng.randint(20),
            "capital_gain": gain,
            "capital_loss": int(rng.choice([0, 0, 2000])),
            "hours_per_week": hours,
            "label": int(score + rng.rand() * 2 > 4),
        })
    return records
