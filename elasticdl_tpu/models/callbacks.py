"""Training callbacks (parity:
elasticdl/python/elasticdl/callbacks.py:23-109).

``ModelExporter`` is the SavedModel-exporter equivalent: it runs on the one
worker that receives the train-end callback task and writes a standalone
export — a ``model.npz`` of merged parameters plus a JSON manifest — that
inference code can load without the framework.  When a PS checkpoint dir is
given, the latest PS-side state (incl. embedding tables) is merged in, the
reference's checkpoint-merge export path (model_handler.py:242-269).
"""

import json
import os

import numpy as np

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ModelExporter:
    def __init__(self, export_dir, checkpoint_dir=None, model_name="",
                 versioned=False, quantize=None):
        """With ``versioned`` the export lands in
        ``export_dir/<trainer.version>/`` (the TF-Serving layout), so a
        live ``serving.server`` pointed at ``export_dir`` hot-swaps to
        it; otherwise ``export_dir`` itself is the export (flat, the
        historical layout).  ``quantize="int8"``: weights-only int8
        servable (serving/export.py)."""
        self.export_dir = export_dir
        self.checkpoint_dir = checkpoint_dir
        self.model_name = model_name
        self.versioned = versioned
        self.quantize = quantize

    def _merged_embeddings(self):
        """({table: (ids, values)}, dense, version) from the latest PS
        checkpoint (version None when there is no checkpoint)."""
        embeddings = {}
        if not self.checkpoint_dir:
            return embeddings, {}, None
        from elasticdl_tpu.utils.checkpoint import CheckpointSaver

        saver = CheckpointSaver(self.checkpoint_dir)
        try:
            ckpt_dense, ckpt_emb, version = saver.load()
        except FileNotFoundError:
            logger.warning("no checkpoint to merge for export")
            return embeddings, {}, None
        for name, (ids, values) in ckpt_emb.items():
            if name.startswith("slot:"):
                continue  # optimizer state is not part of the model
            embeddings[name] = (ids, values)
        return embeddings, ckpt_dense, version

    def on_train_end(self, trainer):
        export_dir = self.export_dir
        if self.versioned:
            export_dir = os.path.join(
                export_dir, str(getattr(trainer, "version", 0)))
        embeddings, ckpt_dense, ckpt_version = self._merged_embeddings()
        if (
            ckpt_dense
            and ckpt_version is not None
            and ckpt_version < getattr(trainer, "version", 0)
        ):
            # The trainer's in-memory train-end params are NEWER than the
            # last checkpoint (collective trainer with a checkpoint_dir):
            # overriding name/shape-matching params would export stale
            # weights.  Keep only PS-side names the trainer doesn't hold.
            trainer_names = set(dict(trainer.export_parameters()))
            ckpt_dense = {
                n: v for n, v in ckpt_dense.items()
                if n not in trainer_names
            }
        bundle = trainer.serving_bundle()
        if bundle is not None:
            # Preferred: standalone servable (StableHLO + npz weights,
            # serving/export.py) — the SavedModel-role artifact.
            from elasticdl_tpu.serving import export_servable

            infer_fn, params, example = bundle
            export_servable(
                export_dir, infer_fn, params, example,
                model_name=self.model_name,
                version=getattr(trainer, "version", 0),
                embeddings=embeddings,
                dense_overrides=ckpt_dense,
                quantize=self.quantize,
            )
            return
        # Fallback (no bundle): weights-only v1 export.
        if self.quantize:
            logger.warning(
                "quantize=%r ignored: the v1 weights-only fallback "
                "export does not quantize (no serving bundle from "
                "this trainer)", self.quantize)
        os.makedirs(export_dir, exist_ok=True)
        payload = dict(trainer.export_parameters())
        payload.update(ckpt_dense)
        flat_emb = {}
        for name, (ids, values) in embeddings.items():
            flat_emb["emb_ids/" + name] = ids
            flat_emb["emb_vals/" + name] = values
        path = os.path.join(export_dir, "model.npz")
        with open(path, "wb") as f:
            np.savez(f, **payload, **flat_emb)
        manifest = {
            "model_name": self.model_name,
            "format": "elasticdl_tpu_export_v1",
            "parameters": sorted(payload),
            "embedding_tables": sorted(embeddings),
            "version": getattr(trainer, "version", 0),
        }
        with open(os.path.join(export_dir, "manifest.json"),
                  "w") as f:
            json.dump(manifest, f, indent=2)
        logger.info("exported model to %s (%d tensors)",
                    export_dir, len(payload))


def load_export(export_dir):
    """Load an export back into ({name: array}, {table: (ids, values)});
    int8-quantized weights and tables dequantize transparently, so a
    quantized export works everywhere a full one does (e.g. as a LoRA
    ``base_export``).  One shared decode: serving.export.load_payload."""
    from elasticdl_tpu.serving.export import load_payload

    return load_payload(export_dir)


class LearningRateScheduler:
    """Schedule the learning rate by model version (parity:
    callbacks.py:69-109).  For the PS path the scheduled lr rides the
    push_gradients message; for collective training prefer an optax
    schedule baked into the optimizer."""

    def __init__(self, schedule_fn):
        self.schedule_fn = schedule_fn

    def on_train_batch_begin(self, trainer):
        lr = float(self.schedule_fn(getattr(trainer, "version", 0)))
        if hasattr(trainer, "_learning_rate"):
            trainer._learning_rate = lr
        return lr
