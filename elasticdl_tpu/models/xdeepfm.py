"""xDeepFM (CIN + DNN + linear) — dac_ctr zoo parity.

The Compressed Interaction Network runs as einsums over the PS-served
factor table; same feature convention as deepfm.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.models import deepfm as _ctr
from elasticdl_tpu.utils import metrics

EMB_TABLE = "xdeepfm_embedding"
LIN_TABLE = "xdeepfm_linear"


def init_params(rng, num_dense, num_fields, embedding_dim,
                cin_sizes=(16, 16), hidden=(128, 64)):
    keys = jax.random.split(rng, len(cin_sizes) + len(hidden) + 2)
    params = {}
    prev = num_fields
    for i, h in enumerate(cin_sizes):
        params["cin_w%d" % i] = (
            jax.random.normal(keys[i], (prev, num_fields, h))
            * (1.0 / np.sqrt(prev * num_fields))
        ).astype(jnp.float32)
        prev = h
    sizes = [num_fields * embedding_dim + num_dense] + list(hidden)
    for i in range(len(hidden)):
        params["deep_w%d" % i] = (
            jax.random.normal(keys[len(cin_sizes) + i],
                              (sizes[i], sizes[i + 1]))
            * np.sqrt(2.0 / sizes[i])
        ).astype(jnp.float32)
        params["deep_b%d" % i] = jnp.zeros((sizes[i + 1],), jnp.float32)
    out_dim = sum(cin_sizes) + sizes[-1]
    params["out_w"] = (
        jax.random.normal(keys[-1], (out_dim, 1)) * 0.01
    ).astype(jnp.float32)
    params["out_b"] = jnp.zeros((1,), jnp.float32)
    return params


def forward(params, feats, train):
    x0 = feats["emb__" + EMB_TABLE][feats["idx__" + EMB_TABLE]]  # [B,F,k]
    first = feats["emb__" + LIN_TABLE][feats["idx__" + LIN_TABLE]][
        ..., 0
    ].sum(axis=1)                                                # [B]
    # CIN: X^l[b,h,k] = sum_ij W[l][i,j,h] X^{l-1}[b,i,k] X^0[b,j,k]
    pooled = []
    x = x0
    n_cin = sum(1 for k in params if k.startswith("cin_w"))
    for i in range(n_cin):
        x = jnp.einsum("bik,bjk,ijh->bhk", x, x0,
                       params["cin_w%d" % i])
        pooled.append(x.sum(axis=-1))                            # [B,H]
    cin_out = jnp.concatenate(pooled, axis=-1)
    # DNN
    h = x0.reshape(x0.shape[0], -1)
    if feats.get("dense") is not None:
        h = jnp.concatenate([h, feats["dense"]], axis=-1)
    n_deep = sum(1 for k in params if k.startswith("deep_w"))
    for i in range(n_deep):
        h = jax.nn.relu(h @ params["deep_w%d" % i]
                        + params["deep_b%d" % i])
    out = jnp.concatenate([cin_out, h], axis=-1) @ params["out_w"]
    return first + out[:, 0] + params["out_b"][0]


def model_spec(num_dense=4, num_fields=8, vocab_size=10000,
               embedding_dim=8, cin_sizes=(16, 16), hidden=(128, 64),
               learning_rate=1e-3):
    def init_fn(rng):
        return init_params(rng, num_dense, num_fields, embedding_dim,
                           cin_sizes, hidden)

    def loss_fn(logits, labels):
        return optax.sigmoid_binary_cross_entropy(
            logits, labels.astype(jnp.float32)
        )

    def feed(records):
        dense = np.stack([np.asarray(r[0], np.float32) for r in records])
        ids = np.stack([np.asarray(r[1], np.int64) for r in records])
        labels = np.asarray([int(r[2]) for r in records], np.int32)
        return (
            {"dense": dense,
             "__ids__": {EMB_TABLE: ids, LIN_TABLE: ids}},
            labels,
        )

    return ModelSpec(
        name="xdeepfm",
        init_fn=init_fn,
        apply_fn=lambda p, f, t: forward(p, f, t),
        loss_fn=loss_fn,
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {
            "auc": metrics.AUC(),
            "accuracy": metrics.BinaryAccuracy(threshold=0.0),
        },
        ps_embedding_infos=[
            {"name": EMB_TABLE, "dim": embedding_dim,
             "initializer": "uniform"},
            {"name": LIN_TABLE, "dim": 1, "initializer": "zeros"},
        ],
        ps_optimizer=("adam", "learning_rate=%g" % learning_rate),
    )


synthetic_data = _ctr.synthetic_data
