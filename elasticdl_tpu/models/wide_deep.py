"""Census wide & deep — model_zoo/census_wide_deep_model parity, built on
the preprocessing library (Hashing / Discretization / IndexLookup /
ConcatenateWithOffset feed the id space, exactly the reference's census
feature-engineering pattern).

Works from the census CSV column layout (age, workclass, education, ...,
label) or from the synthetic generator below.  Embeddings are PS-served:
a dim-k deep table and a dim-1 wide (linear) table over one concatenated
id space.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models import mlp
from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.preprocessing.layers import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    ToNumber,
)
from elasticdl_tpu.utils import metrics

DEEP_TABLE = "wide_deep_embedding"
WIDE_TABLE = "wide_deep_linear"

# (name, kind, arg): numeric columns get bucket boundaries, categorical
# columns get a hash-bin count.
CENSUS_FEATURES = [
    ("age", "numeric", [18, 25, 30, 35, 40, 45, 50, 55, 60, 65]),
    ("workclass", "categorical", 64),
    ("education", "categorical", 64),
    ("marital_status", "categorical", 32),
    ("occupation", "categorical", 128),
    ("relationship", "categorical", 32),
    ("race", "categorical", 16),
    ("sex", "categorical", 4),
    ("hours_per_week", "numeric", [20, 30, 40, 50, 60]),
    ("native_country", "categorical", 128),
]


def _field_sizes():
    sizes = []
    for _, kind, arg in CENSUS_FEATURES:
        sizes.append(len(arg) + 1 if kind == "numeric" else arg)
    return sizes


def build_feed():
    """records: list of CSV rows [col0, ..., colN, label]."""
    sizes = _field_sizes()
    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]]).tolist()
    to_number = ToNumber(np.float64, default_value=0)
    transforms = []
    for _, kind, arg in CENSUS_FEATURES:
        if kind == "numeric":
            transforms.append(
                lambda col, d=Discretization(arg): d(to_number(col))
            )
        else:
            transforms.append(Hashing(num_bins=arg))

    def feed(records):
        columns = list(zip(*records))
        id_cols = []
        for i, transform in enumerate(transforms):
            col = np.asarray(columns[i], dtype=object).reshape(-1, 1)
            id_cols.append(np.asarray(transform(col)))
        ids = ConcatenateWithOffset(offsets=offsets, axis=1)(id_cols)
        labels = np.asarray(
            [int(float(v)) for v in columns[-1]], np.int32
        )
        return {"__ids__": {DEEP_TABLE: ids.astype(np.int64),
                            WIDE_TABLE: ids.astype(np.int64)}}, labels

    return feed, int(sum(sizes))


def init_params(rng, num_fields, embedding_dim, hidden=(64, 32)):
    sizes = [num_fields * embedding_dim] + list(hidden) + [1]
    params = mlp.mlp_init(rng, sizes)
    params["bias"] = jnp.zeros((1,), jnp.float32)
    return params


def forward(params, feats, train):
    deep_v = feats["emb__" + DEEP_TABLE][feats["idx__" + DEEP_TABLE]]
    wide = feats["emb__" + WIDE_TABLE][feats["idx__" + WIDE_TABLE]][
        ..., 0
    ].sum(axis=1)
    x = deep_v.reshape(deep_v.shape[0], -1)
    return wide + mlp.mlp_apply(params, x)[:, 0] + params["bias"][0]


def model_spec(embedding_dim=8, hidden=(64, 32), learning_rate=1e-3):
    feed, vocab_size = build_feed()
    num_fields = len(CENSUS_FEATURES)

    def init_fn(rng):
        return init_params(rng, num_fields, embedding_dim, hidden)

    def loss_fn(logits, labels):
        return optax.sigmoid_binary_cross_entropy(
            logits, labels.astype(jnp.float32)
        )

    return ModelSpec(
        name="census_wide_deep",
        init_fn=init_fn,
        apply_fn=lambda p, f, t: forward(p, f, t),
        loss_fn=loss_fn,
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {
            "auc": metrics.AUC(),
            "accuracy": metrics.BinaryAccuracy(threshold=0.0),
        },
        ps_embedding_infos=[
            {"name": DEEP_TABLE, "dim": embedding_dim,
             "initializer": "uniform"},
            {"name": WIDE_TABLE, "dim": 1, "initializer": "zeros"},
        ],
        ps_optimizer=("adam", "learning_rate=%g" % learning_rate),
    )


def synthetic_census_rows(n=1024, seed=0):
    """CSV-shaped census-like rows with a learnable label rule."""
    rng = np.random.RandomState(seed)
    workclasses = ["private", "gov", "self", "none"]
    educations = ["hs", "college", "masters", "phd", "other"]
    rows = []
    for _ in range(n):
        age = int(rng.randint(17, 80))
        wc = workclasses[rng.randint(len(workclasses))]
        edu = educations[rng.randint(len(educations))]
        marital = ["single", "married", "divorced"][rng.randint(3)]
        occ = "occ%d" % rng.randint(12)
        rel = ["own", "spouse", "child"][rng.randint(3)]
        race = "race%d" % rng.randint(4)
        sex = ["m", "f"][rng.randint(2)]
        hours = int(rng.randint(10, 80))
        country = "c%d" % rng.randint(20)
        score = (
            (age > 35) + (edu in ("masters", "phd")) * 2
            + (hours > 45) + (marital == "married")
        )
        label = int(score + rng.rand() * 1.5 > 3)
        rows.append([
            str(age), wc, edu, marital, occ, rel, race, sex,
            str(hours), country, str(label),
        ])
    return rows
