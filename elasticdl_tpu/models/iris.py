"""Iris DNN classifier from CSV — model_zoo iris/heart-style simple
tabular model (reference model_zoo/iris, odps_iris)."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.utils import metrics


class IrisDNN(nn.Module):
    num_classes: int = 3

    @nn.compact
    def __call__(self, x, train=True):
        x = nn.relu(nn.Dense(16)(x))
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(self.num_classes)(x)


def feed(records):
    """records: CSV rows [f0, f1, f2, f3, label]."""
    xs = np.asarray(
        [[float(v) for v in r[:4]] for r in records], np.float32
    )
    ys = np.asarray([int(float(r[4])) for r in records], np.int32)
    return xs, ys


def model_spec(learning_rate=0.01, num_classes=3):
    model = IrisDNN(num_classes=num_classes)

    def init_fn(rng):
        return model.init(rng, jnp.zeros((1, 4)))["params"]

    return ModelSpec(
        name="iris",
        init_fn=init_fn,
        apply_fn=lambda p, x, t: model.apply({"params": p}, x, train=t),
        loss_fn=lambda logits, labels:
            optax.softmax_cross_entropy_with_integer_labels(logits,
                                                            labels),
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {"accuracy": metrics.Accuracy()},
    )


def synthetic_iris_csv(path, n=150, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.array([[5.0, 3.4, 1.5, 0.2], [5.9, 2.8, 4.3, 1.3],
                        [6.6, 3.0, 5.6, 2.1]])
    with open(path, "w") as f:
        for _ in range(n):
            y = rng.randint(3)
            x = centers[y] + rng.randn(4) * 0.25
            f.write(",".join("%.2f" % v for v in x) + ",%d\n" % y)
    return path
