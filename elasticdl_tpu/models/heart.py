"""Heart-disease tabular classifier — model_zoo heart parity
(13-feature CSV, binary label)."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.utils import metrics

NUM_FEATURES = 13


class HeartDNN(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        x = nn.relu(nn.Dense(32)(x))
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(1)(x)[:, 0]


def feed(records):
    xs = np.asarray(
        [[float(v) for v in r[:NUM_FEATURES]] for r in records],
        np.float32,
    )
    ys = np.asarray(
        [int(float(r[NUM_FEATURES])) for r in records], np.int32
    )
    return xs, ys


def model_spec(learning_rate=0.005):
    model = HeartDNN()

    def init_fn(rng):
        return model.init(rng, jnp.zeros((1, NUM_FEATURES)))["params"]

    return ModelSpec(
        name="heart",
        init_fn=init_fn,
        apply_fn=lambda p, x, t: model.apply({"params": p}, x, train=t),
        loss_fn=lambda logits, labels: optax.sigmoid_binary_cross_entropy(
            logits, labels.astype(jnp.float32)
        ),
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {
            "auc": metrics.AUC(),
            "accuracy": metrics.BinaryAccuracy(threshold=0.0),
        },
    )


def synthetic_heart_csv(path, n=300, seed=0):
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        for _ in range(n):
            x = rng.randn(NUM_FEATURES)
            y = int(x[0] + 0.8 * x[3] - 0.5 * x[7] + rng.randn() * 0.3
                    > 0)
            f.write(",".join("%.3f" % v for v in x) + ",%d\n" % y)
    return path
