"""Shared He-init MLP used by the tabular/CTR zoo models.

One implementation of the ``w%d``/``b%d`` dense stack that census_dnn,
census_sqlflow, and wide_deep previously each re-implemented (the param
naming is part of those models' checkpoint format, so it is preserved
here).
"""

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(rng, sizes):
    """He-initialized params {"w0","b0",...} for the layer widths
    ``sizes`` ([in, hidden..., out])."""
    keys = jax.random.split(rng, max(2, len(sizes) - 1))
    params = {}
    for i in range(len(sizes) - 1):
        params["w%d" % i] = (
            jax.random.normal(keys[i], (sizes[i], sizes[i + 1]))
            * np.sqrt(2.0 / sizes[i])
        ).astype(jnp.float32)
        params["b%d" % i] = jnp.zeros((sizes[i + 1],), jnp.float32)
    return params


def mlp_apply(params, x):
    """Dense stack with ReLU between layers (linear final layer).
    Ignores params outside the w%d/b%d convention, so models may mix
    extra keys (e.g. a global "bias") into the same dict."""
    n_layers = sum(1 for k in params if k.startswith("w"))
    for i in range(n_layers):
        x = x @ params["w%d" % i] + params["b%d" % i]
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    return x
