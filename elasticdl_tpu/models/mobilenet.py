"""MobileNetV2 — the reference's second CIFAR-10 benchmark model
(docs/benchmark/ftlib_benchmark.md:45-51, 83-86: 2,236,682 params).

Same TPU-first conventions as resnet.py: NHWC, GroupNorm, bf16 compute via
the trainer.  Depthwise convs use feature_group_count (XLA lowers these to
efficient TPU convolutions).
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.utils import metrics


def _gn(channels):
    return nn.GroupNorm(num_groups=int(np.gcd(8, channels)))


class InvertedResidual(nn.Module):
    filters: int
    stride: int
    expand_ratio: int

    @nn.compact
    def __call__(self, x):
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand_ratio
        y = x
        if self.expand_ratio != 1:
            y = nn.Conv(hidden, (1, 1), use_bias=False)(y)
            y = _gn(hidden)(y)
            y = nn.relu6(y)
        y = nn.Conv(
            hidden, (3, 3), strides=(self.stride, self.stride),
            padding="SAME", feature_group_count=hidden, use_bias=False,
        )(y)
        y = _gn(hidden)(y)
        y = nn.relu6(y)
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
        y = _gn(self.filters)(y)
        if self.stride == 1 and in_ch == self.filters:
            y = y + x
        return y


class MobileNetV2(nn.Module):
    num_classes: int = 10
    width_mult: float = 1.0
    cifar_stem: bool = True

    # (expand_ratio, channels, repeats, stride)
    config: tuple = (
        (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
    )

    @nn.compact
    def __call__(self, x, train=True):
        def c(ch):
            return max(8, int(ch * self.width_mult))

        stem_stride = 1 if self.cifar_stem else 2
        x = nn.Conv(c(32), (3, 3), strides=(stem_stride, stem_stride),
                    padding="SAME", use_bias=False)(x)
        x = _gn(c(32))(x)
        x = nn.relu6(x)
        for expand, ch, repeats, stride in self.config:
            for i in range(repeats):
                x = InvertedResidual(
                    filters=c(ch),
                    stride=stride if i == 0 else 1,
                    expand_ratio=expand,
                )(x)
        x = nn.Conv(c(1280), (1, 1), use_bias=False)(x)
        x = _gn(c(1280))(x)
        x = nn.relu6(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def model_spec(num_classes=10, image_size=32, learning_rate=0.05,
               cifar_stem=True):
    model = MobileNetV2(num_classes=num_classes, cifar_stem=cifar_stem)

    def init_fn(rng):
        return model.init(
            rng, jnp.zeros((1, image_size, image_size, 3))
        )["params"]

    def apply_fn(params, x, train):
        return model.apply({"params": params}, x, train=train)

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        )

    def feed(records):
        xs = np.stack([np.asarray(r[0], np.float32) for r in records])
        ys = np.asarray([int(r[1]) for r in records], np.int32)
        return xs, ys

    return ModelSpec(
        name="mobilenetv2",
        init_fn=init_fn,
        apply_fn=apply_fn,
        loss_fn=loss_fn,
        optimizer=optax.sgd(learning_rate, momentum=0.9),
        feed=feed,
        eval_metrics_fn=lambda: {"accuracy": metrics.Accuracy()},
    )
