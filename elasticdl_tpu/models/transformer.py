"""Flagship decoder-only Transformer LM with 4-axis parallelism.

Pure-JAX (explicit param pytree + PartitionSpec tree) so every sharding
decision is visible:

 - ``dp``: batch data parallelism (gradient psum inserted by XLA)
 - ``tp``: Megatron-style tensor parallelism — attention heads and MLP
   hidden are column/row sharded; XLA places the reduce-scatter/all-reduce
 - ``sp``: sequence parallelism — activations carry a seq-dim sharding and
   attention runs as ring attention over the ICI ring
   (elasticdl_tpu/parallel/ring_attention.py)
 - ``pp``: layer-stage sharding — the scanned layer stack's leading axis is
   sharded over ``pp`` so each stage group holds only its layers' weights
   (memory-parallel; microbatch pipelining can layer on top)

The reference has no model parallelism at all beyond PS-sharded embeddings
(SURVEY.md §2.12); this module is the deliberate TPU-native design for it.
RoPE positions, pre-norm RMSNorm, SwiGLU MLP.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.parallel.ring_attention import ring_attention
from elasticdl_tpu.utils import metrics


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    dim: int = 512
    num_heads: int = 8
    num_layers: int = 4
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    dtype: str = "bfloat16"
    tied_embeddings: bool = True
    # Mixture-of-experts: 0 = dense FFN; >0 = top-k routing with experts
    # sharded over the ``ep`` mesh axis and a Switch-style auxiliary
    # load-balance loss (weight ``moe_aux_weight``) to stop router
    # collapse.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    # Rematerialize each scanned layer in the backward pass instead of
    # saving its activations — O(1)-layers activation memory for ~1/3
    # more FLOPs.  Required to fit training-scale configs (24 layers x
    # T=2048 saves ~20 GB of activations un-remat'ed on one chip).
    # True = save nothing; "dots" = save matmul outputs and recompute
    # only the cheap elementwise work (more memory, fewer re-FLOPs);
    # "attn" = save only the attention outputs (B*T*dim per layer), so
    # the recompute skips flash attention but everything else remats.
    remat: bool | str = False
    # Sequence-parallel strategy over the ``sp`` mesh axis: "ring"
    # (ppermute K/V streaming, parallel/ring_attention.py) or "ulysses"
    # (all-to-all head/sequence re-sharding, parallel/ulysses.py;
    # requires (heads/tp) % sp == 0).
    attention_impl: str = "ring"
    # Sliding-window causal attention: 0 = full causal; W > 0 keeps only
    # the last W positions (O(T·W) attention compute — out-of-band
    # blocks skip matmuls and DMA in the flash kernel, and whole ring
    # steps skip when the shard lies past the band).
    window: int = 0
    # Grouped-query attention: 0 = MHA (kv heads == num_heads); G > 0
    # projects K/V to G heads and each group of num_heads/G query heads
    # shares one — smaller wk/wv params + projection FLOPs, and the
    # G-head KV cache is the standard serving memory win.  Q heads are
    # grouped consecutively (head i attends kv head i // (H/G)).
    num_kv_heads: int = 0

    @property
    def head_dim(self):
        return self.dim // self.num_heads

    @property
    def kv_heads(self):
        """Effective K/V head count (num_kv_heads=0 -> MHA)."""
        kv = self.num_kv_heads or self.num_heads
        if kv <= 0 or self.num_heads % kv:
            raise ValueError(
                "num_heads (%d) must be a positive multiple of "
                "num_kv_heads (%d)" % (self.num_heads, kv))
        return kv

    @property
    def mlp_dim(self):
        return self.dim * self.mlp_ratio


# -- parameters --------------------------------------------------------------


def init_params(rng, cfg):
    """Layer weights are stacked on a leading [num_layers] axis (scanned)."""
    k_embed, k_attn, k_mlp, k_out = jax.random.split(rng, 4)
    L, E, H, D, F = (cfg.num_layers, cfg.dim, cfg.num_heads,
                     cfg.head_dim, cfg.mlp_dim)

    def norm_init(*shape):
        return jnp.ones(shape, jnp.float32)

    def dense_init(key, *shape, scale=None):
        fan_in = shape[-2] if len(shape) >= 2 else shape[0]
        scale = scale or (1.0 / np.sqrt(fan_in))
        return jax.random.normal(key, shape, jnp.float32) * scale

    keys = jax.random.split(k_attn, 6)
    G = cfg.kv_heads
    layers = {
        "ln1": norm_init(L, E),
        "wq": dense_init(keys[0], L, E, H * D),
        "wk": dense_init(keys[1], L, E, G * D),
        "wv": dense_init(keys[2], L, E, G * D),
        "wo": dense_init(keys[3], L, H * D, E),
        "ln2": norm_init(L, E),
    }
    if cfg.moe_experts:
        X = cfg.moe_experts
        layers["w_router"] = dense_init(keys[4], L, E, X, scale=0.02)
        layers["w_gate"] = dense_init(keys[5], L, X, E, F)
        layers["w_up"] = dense_init(jax.random.fold_in(k_mlp, 0),
                                    L, X, E, F)
        layers["w_down"] = dense_init(jax.random.fold_in(k_mlp, 1),
                                      L, X, F, E)
    else:
        layers["w_gate"] = dense_init(keys[4], L, E, F)
        layers["w_up"] = dense_init(keys[5], L, E, F)
        layers["w_down"] = dense_init(jax.random.fold_in(k_mlp, 1),
                                      L, F, E)
    params = {
        "embed": dense_init(k_embed, cfg.vocab_size, E, scale=0.02),
        "layers": layers,
        "ln_f": norm_init(E),
    }
    if not cfg.tied_embeddings:
        params["lm_head"] = dense_init(k_out, E, cfg.vocab_size, scale=0.02)
    return params


def param_specs(cfg):
    """PartitionSpec tree matching init_params' structure."""
    layers = {
        "ln1": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "ln2": P("pp", None),
    }
    if cfg.moe_experts:
        layers["w_router"] = P("pp", None, None)
        layers["w_gate"] = P("pp", "ep", None, "tp")
        layers["w_up"] = P("pp", "ep", None, "tp")
        layers["w_down"] = P("pp", "ep", "tp", None)
    else:
        layers["w_gate"] = P("pp", None, "tp")
        layers["w_up"] = P("pp", None, "tp")
        layers["w_down"] = P("pp", "tp", None)
    specs = {
        "embed": P(None, "tp"),
        "layers": layers,
        "ln_f": P(None),
    }
    if not cfg.tied_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def shard_params(params, mesh, cfg):
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


# -- forward ------------------------------------------------------------------


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def _rope(x, positions):
    """Rotary embeddings; x: [B, T, H, D]."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(
        -np.log(10000.0) * jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


def _moe_ffn(h, w, cfg, mesh):
    """Top-k MoE FFN (expert weights sharded over ``ep``).

    Dense dispatch/combine einsum formulation (Mesh-TensorFlow style):
    per-sequence expert capacity bounds compute; overflow tokens fall to
    lower-priority choices or the residual.  Returns (out, aux) where
    aux is the Switch-Transformer load-balance loss
    X * sum_x fraction_top1(x) * mean_prob(x) — 1.0 at perfect balance,
    approaching X under router collapse — so minimizing it pushes the
    router toward uniform utilization.
    """
    B, T, E = h.shape
    X = cfg.moe_experts
    K = min(cfg.moe_top_k, X)
    # K choices per token -> expected per-expert load is K*T/X.
    capacity = max(
        1, min(T, int(T * K * cfg.moe_capacity_factor / X) + 1)
    )
    logits = h @ w["w_router"].astype(h.dtype)            # [B,T,X]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # Switch aux loss from the top-1 assignment (computed before
    # capacity so it reflects router intent, not dispatch truncation).
    # frac/mean_probs are the LINEAR sufficient statistics — callers
    # that accumulate across microbatches (the pipeline) combine them
    # at the end for the exact full-batch aux.
    top1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1), X,
                          dtype=jnp.float32)
    frac_tokens = top1.mean(axis=(0, 1))                  # [X]
    mean_probs = probs.mean(axis=(0, 1))                  # [X]
    stats = jnp.stack([frac_tokens, mean_probs])          # [2, X]
    aux = X * jnp.sum(frac_tokens * mean_probs)

    gate_vals, experts = jax.lax.top_k(probs, K)          # [B,T,K]
    if K > 1:
        # GShard-style renormalization over the chosen experts.  Top-1
        # keeps the raw p_top1 gate (Switch): renormalizing would make
        # it identically 1.0 and cut the router out of the task loss.
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(axis=-1, keepdims=True), 1e-9
        )

    # Per-expert capacity slots: choice 0 has priority; choice j's
    # positions start after all previous choices' tokens for that expert.
    onehots = [
        jax.nn.one_hot(experts[..., j], X, dtype=jnp.float32)
        for j in range(K)
    ]
    disp = 0.0      # 0/1 dispatch  [B,T,X,C]
    combine = 0.0   # gate-weighted combine  [B,T,X,C]
    offset = jnp.zeros((B, 1, X), jnp.float32)
    for j in range(K):
        pos = jnp.cumsum(onehots[j], axis=1) - 1.0 + offset   # [B,T,X]
        keep = onehots[j] * (pos < capacity)
        slot = keep[..., None] * jax.nn.one_hot(
            jnp.clip(pos, 0, capacity - 1).astype(jnp.int32),
            capacity, dtype=jnp.float32,
        )
        disp = disp + slot
        combine = combine + gate_vals[..., j, None, None] * slot
        offset = offset + onehots[j].sum(axis=1, keepdims=True)
    if mesh is not None:
        disp = _constrain(disp, mesh, P("dp", "sp", "ep", None))
        combine = _constrain(combine, mesh, P("dp", "sp", "ep", None))
    xin = jnp.einsum("btxc,bte->xbce", disp, h.astype(jnp.float32))
    xin = xin.astype(h.dtype)
    if mesh is not None:
        xin = _constrain(xin, mesh, P("ep", "dp", None, None))
    g = jax.nn.silu(
        jnp.einsum("xbce,xef->xbcf", xin, w["w_gate"].astype(h.dtype))
    )
    u = jnp.einsum("xbce,xef->xbcf", xin, w["w_up"].astype(h.dtype))
    y = jnp.einsum("xbcf,xfe->xbce", g * u,
                   w["w_down"].astype(h.dtype))
    out = jnp.einsum("btxc,xbce->bte", combine,
                     y.astype(jnp.float32))
    return out.astype(h.dtype), aux, stats


def _constrain(x, mesh, spec):
    if mesh is not None:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec)
        )
    return x


def _layer_body(x, w, cfg, mesh, positions, attention_mode=None,
                moe_stats=False, return_kv=False):
    """One transformer block; shared by the scanned stack (forward) and
    the per-stage slice scan (forward_pipelined).  ``moe_stats`` swaps
    the scalar aux for the linear [2, X] router statistics (pipeline
    accumulation).  ``return_kv`` additionally returns this layer's
    post-RoPE, pre-GQA-expand (k, v) [B, T, G, D] — the decode prefill
    captures them into the KV cache."""
    compute_dtype = jnp.dtype(cfg.dtype)
    act_spec = P("dp", "sp", None)
    B, T = x.shape[0], x.shape[1]
    H, D = cfg.num_heads, cfg.head_dim
    G = cfg.kv_heads
    h = _rmsnorm(x, w["ln1"].astype(compute_dtype))
    q = (h @ w["wq"].astype(compute_dtype)).reshape(B, T, H, D)
    k = (h @ w["wk"].astype(compute_dtype)).reshape(B, T, G, D)
    v = (h @ w["wv"].astype(compute_dtype)).reshape(B, T, G, D)
    q = _rope(q, positions)
    k = _rope(k, positions)
    kv_out = (k, v) if return_kv else None
    if G != H:
        # GQA: expand K/V to the full head count for the (unchanged)
        # attention kernels.  jnp.repeat keeps group order consecutive,
        # matching the q-head grouping convention (head i -> kv head
        # i // (H/G)); XLA lowers this to a broadcast feeding the
        # score matmuls.
        k = jnp.repeat(k, H // G, axis=2)
        v = jnp.repeat(v, H // G, axis=2)
    if mesh is None and attention_mode is not None:
        from elasticdl_tpu.parallel.ring_attention import attention_local

        attn = attention_local(q, k, v, causal=True, mode=attention_mode,
                               window=cfg.window)
    elif cfg.attention_impl == "ulysses":
        from elasticdl_tpu.parallel.ulysses import ulysses_attention

        attn = ulysses_attention(q, k, v, mesh, causal=True,
                                 window=cfg.window)
    elif cfg.attention_impl == "ring":
        attn = ring_attention(q, k, v, mesh, causal=True,
                              window=cfg.window)
    else:
        raise ValueError(
            "unknown attention_impl %r (want 'ring' or 'ulysses')"
            % (cfg.attention_impl,)
        )
    attn = attn.reshape(B, T, H * D)
    # Named so remat="attn" can save exactly this tensor: the layer
    # recompute in the backward then skips re-running flash attention
    # (the score-matmul ~40% of layer FLOPs at T=2048) while saving
    # only B*T*dim per layer instead of every intermediate.
    from jax.ad_checkpoint import checkpoint_name

    attn = checkpoint_name(attn, "attn_out")
    x = x + _constrain(
        attn @ w["wo"].astype(compute_dtype), mesh, act_spec
    )
    h = _rmsnorm(x, w["ln2"].astype(compute_dtype))
    if cfg.moe_experts:
        moe_out, aux, stats = _moe_ffn(h, w, cfg, mesh)
        x = x + _constrain(moe_out, mesh, act_spec)
        if moe_stats:
            return (x, (stats, kv_out)) if return_kv else (x, stats)
    else:
        gate = jax.nn.silu(h @ w["w_gate"].astype(compute_dtype))
        up = h @ w["w_up"].astype(compute_dtype)
        x = x + _constrain(
            (gate * up) @ w["w_down"].astype(compute_dtype), mesh,
            act_spec,
        )
        aux = jnp.float32(0.0)
    if return_kv:
        return x, (aux, kv_out)
    return x, aux


def _head(params, x, cfg):
    compute_dtype = jnp.dtype(cfg.dtype)
    x = _rmsnorm(x, params["ln_f"].astype(compute_dtype))
    head = (
        params["embed"].T if cfg.tied_embeddings else params["lm_head"]
    ).astype(compute_dtype)
    return (x @ head).astype(jnp.float32)


def forward_hidden(params, tokens, cfg, mesh=None):
    """tokens: [B, T] int32 -> (final hidden [B, T, dim] BEFORE the
    ln_f/head, mean per-layer MoE aux).

    Pair with :func:`next_token_loss_chunked` to train without ever
    materializing the [B, T, V] logits tensor (at the flagship config
    that tensor is ~2 GB in f32 — a pure HBM-bandwidth tax the chunked
    loss removes).
    """
    compute_dtype = jnp.dtype(cfg.dtype)
    act_spec = P("dp", "sp", None)

    x = params["embed"].astype(compute_dtype)[tokens]
    x = _constrain(x, mesh, act_spec)
    positions = jnp.arange(tokens.shape[1])

    def layer(x, w):
        return _layer_body(x, w, cfg, mesh, positions)

    if cfg.remat == "dots":
        layer = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    elif cfg.remat == "attn":
        layer = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out"
            ),
        )
    elif cfg.remat:
        layer = jax.checkpoint(layer)
    x, aux_per_layer = jax.lax.scan(layer, x, params["layers"])
    return x, aux_per_layer.mean()


def forward(params, tokens, cfg, mesh=None, return_aux=False):
    """tokens: [B, T] int32 -> logits [B, T, V].

    With ``return_aux`` (training an MoE), also returns the mean
    per-layer load-balance loss for the spec's loss_fn to add.
    """
    x, aux = forward_hidden(params, tokens, cfg, mesh=mesh)
    logits = _head(params, x, cfg)
    if return_aux:
        return logits, aux
    return logits


def forward_pipelined(params, tokens, cfg, mesh, num_microbatches,
                      remat=False, return_aux=False,
                      return_hidden=False):
    """Microbatch-pipelined forward over the ``pp`` mesh axis.

    The layer stack runs as a GPipe schedule (parallel/pipeline.py):
    S = mesh.shape['pp'] stages compute concurrently on different
    microbatches, activations hopping stages via ppermute.  Bubble
    fraction is (S-1)/(M+S-1) — S=2, M=8 -> 11.1%.  With ``return_aux``
    the MoE load-balance loss equals the EXACT full-batch Switch
    statistic: stages accumulate the linear per-expert (frac, prob)
    sufficient statistics over real ticks (bubbles masked) and combine
    them after the loop, so the objective is identical to the scanned
    forward's and independent of the microbatch count.  Embedding
    lookup and
    the LM head run replicated over pp outside the pipeline (their FLOPs
    are small next to the stack).  Attention is per-shard local inside a
    stage, so this path requires sp=1; dp/tp compose as auto axes.
    """
    from elasticdl_tpu.parallel.pipeline import (
        merge_microbatches,
        pipeline_apply,
        split_microbatches,
    )

    if mesh.shape.get("sp", 1) != 1:
        raise ValueError(
            "forward_pipelined requires sp=1 (stage-local attention); "
            "use ring attention (plain forward) for sequence parallelism"
        )
    compute_dtype = jnp.dtype(cfg.dtype)
    x = params["embed"].astype(compute_dtype)[tokens]
    positions = jnp.arange(tokens.shape[1])

    collect_aux = bool(return_aux and cfg.moe_experts)

    def stage_fn(w, x_mb):
        def body(x, w1):
            # attention_mode="off": inside the pp-manual shard_map the
            # dp/tp axes are auto, and a pallas_call under auto axes
            # would be all-gathered by GSPMD; the jnp path partitions.
            return _layer_body(
                x, w1, cfg, None, positions, attention_mode="off",
                moe_stats=collect_aux,
            )

        x_mb, aux_per_layer = jax.lax.scan(body, x_mb, w)
        if collect_aux:
            return x_mb, aux_per_layer  # [L_stage, 2, X] router stats
        return x_mb

    def finalize(stats, num_mb):
        # stats: [L_stage, 2, X] SUMS of per-microbatch (frac, prob)
        # means.  /M gives the full-batch means (equal microbatch
        # sizes), so this stage's layers contribute their EXACT Switch
        # aux — no dependence on M.
        f = stats[:, 0] / num_mb
        p = stats[:, 1] / num_mb
        return (cfg.moe_experts * (f * p).sum(-1)).sum()

    xm = split_microbatches(x, num_microbatches)
    if collect_aux:
        ym, aux_sum = pipeline_apply(
            stage_fn, params["layers"], xm, mesh=mesh,
            num_microbatches=num_microbatches, remat=remat,
            with_aux=True, aux_finalize=finalize,
        )
    else:
        ym = pipeline_apply(
            stage_fn, params["layers"], xm, mesh=mesh,
            num_microbatches=num_microbatches, remat=remat,
        )
    x = merge_microbatches(ym)
    # The head runs on the MERGED hidden states outside the pipeline,
    # so ``return_hidden`` composes with the chunked loss exactly like
    # the scanned forward's forward_hidden.
    out = x if return_hidden else _head(params, x, cfg)
    if return_aux:
        if not collect_aux:  # dense model asked for aux: trivially zero
            return out, jnp.float32(0.0)
        # aux_sum covers ALL layers (stages sum via psum); normalize to
        # mean-per-layer to match forward(return_aux=True).
        return out, aux_sum / cfg.num_layers
    return out


# -- autoregressive decoding ---------------------------------------------------

NEG_INF_DECODE = -1e30


def init_kv_cache(cfg, batch, max_len):
    """Zeroed per-layer K/V caches, each [L, B, max_len, G, D].

    G = cfg.kv_heads: with grouped-query attention the cache holds G
    heads, not num_heads — the standard serving memory win (e.g. G=2,
    H=16 caches 8x less KV).
    """
    shape = (cfg.num_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    dtype = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def _decode_layer(x, w, cfg, ck, cv, pos):
    """One block for ONE position.  x: [B, 1, E]; ck/cv: [B, max, G, D]
    caches (updated at ``pos`` and returned).  Attention is the single
    query against the cache, computed grouped (no K/V head repeat)."""
    compute_dtype = jnp.dtype(cfg.dtype)
    B = x.shape[0]
    H, D, G = cfg.num_heads, cfg.head_dim, cfg.kv_heads
    R = H // G
    positions = jnp.reshape(pos, (1,))
    h = _rmsnorm(x, w["ln1"].astype(compute_dtype))
    q = _rope((h @ w["wq"].astype(compute_dtype)).reshape(B, 1, H, D),
              positions)
    k = _rope((h @ w["wk"].astype(compute_dtype)).reshape(B, 1, G, D),
              positions)
    v = (h @ w["wv"].astype(compute_dtype)).reshape(B, 1, G, D)
    ck = jax.lax.dynamic_update_slice(
        ck, k.astype(ck.dtype), (0, pos, 0, 0))
    cv = jax.lax.dynamic_update_slice(
        cv, v.astype(cv.dtype), (0, pos, 0, 0))

    qg = q.reshape(B, G, R, D).astype(jnp.float32)
    s = jnp.einsum(
        "bgrd,btgd->bgrt", qg, ck.astype(jnp.float32),
    ) * (D ** -0.5)                                   # [B, G, R, max]
    idx = jnp.arange(ck.shape[1])
    valid = idx <= pos
    if cfg.window:
        valid &= (pos - idx) < cfg.window
    s = jnp.where(valid[None, None, None, :], s, NEG_INF_DECODE)
    p = jax.nn.softmax(s, axis=-1)
    attn = jnp.einsum(
        "bgrt,btgd->bgrd", p, cv.astype(jnp.float32)
    ).reshape(B, 1, H * D).astype(compute_dtype)
    x = x + attn @ w["wo"].astype(compute_dtype)

    h = _rmsnorm(x, w["ln2"].astype(compute_dtype))
    if cfg.moe_experts:
        moe_out, _aux, _stats = _moe_ffn(h, w, cfg, None)
        x = x + moe_out
    else:
        gate = jax.nn.silu(h @ w["w_gate"].astype(compute_dtype))
        up = h @ w["w_up"].astype(compute_dtype)
        x = x + (gate * up) @ w["w_down"].astype(compute_dtype)
    return x, ck, cv


def prefill(params, cfg, prompt, max_len):
    """Batched prefill: ONE forward pass over the prompt computes every
    layer's K/V and writes them into fresh caches of length
    ``max_len``.  Returns (last-position logits [B, V], caches).  This
    is the time-to-first-token path — Tp sequential decode steps would
    be MXU-starved serialized work."""
    compute_dtype = jnp.dtype(cfg.dtype)
    b, tp = prompt.shape
    x = params["embed"].astype(compute_dtype)[prompt]
    positions = jnp.arange(tp)

    def layer(x, w):
        x, (_aux, kv) = _layer_body(
            x, w, cfg, None, positions, return_kv=True
        )
        return x, kv

    x, (ks, vs) = jax.lax.scan(layer, x, params["layers"])
    ck, cv = init_kv_cache(cfg, b, max_len)  # [L, B, max, G, D]
    ck = jax.lax.dynamic_update_slice(
        ck, ks.astype(ck.dtype), (0, 0, 0, 0, 0))
    cv = jax.lax.dynamic_update_slice(
        cv, vs.astype(cv.dtype), (0, 0, 0, 0, 0))
    logits = _head(params, x, cfg)[:, -1]
    return logits, (ck, cv)


def decode_step(params, cfg, caches, pos, tokens_1):
    """One decode step: tokens_1 [B] int32 at position ``pos`` ->
    (logits [B, V], updated caches).  ``caches`` from
    :func:`init_kv_cache`."""
    compute_dtype = jnp.dtype(cfg.dtype)
    x = params["embed"].astype(compute_dtype)[tokens_1][:, None, :]

    def body(x, inputs):
        w, ck, cv = inputs
        x, ck, cv = _decode_layer(x, w, cfg, ck, cv, pos)
        return x, (ck, cv)

    x, new_caches = jax.lax.scan(body, x, (params["layers"],) + caches)
    logits = _head(params, x, cfg)[:, 0]
    return logits, new_caches


def generate(params, cfg, prompt, max_new_tokens, temperature=0.0,
             rng=None):
    """Autoregressive generation: batched prefill + KV-cache decode.

    prompt: [B, Tp] int32, Tp >= 1 (seed unconditional generation with
    a BOS token).  Returns [B, Tp + max_new_tokens]; greedy when
    ``temperature`` == 0, else softmax sampling at the given
    temperature.  Positions use RoPE, so sequences may run past
    cfg.max_seq_len (quality, not correctness, degrades).
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, tp = prompt.shape
    if tp == 0:
        raise ValueError(
            "prompt must have at least one token (use a BOS token for "
            "unconditional generation)")
    # Accept numpy-loaded params (e.g. a servable export's npz):
    # indexing a numpy embed table with a traced token id would fail
    # inside the decode scan.
    params = jax.tree_util.tree_map(jnp.asarray, params)
    max_new_tokens = int(max_new_tokens)
    if max_new_tokens == 0:
        return prompt
    total = tp + max_new_tokens
    if rng is None:
        rng = jax.random.PRNGKey(0)
    greedy = not temperature

    def sample(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits / temperature, axis=-1
        ).astype(jnp.int32)

    logits0, caches = prefill(params, cfg, prompt, total)
    rng, sub = jax.random.split(rng)
    first = sample(logits0, sub)
    tokens = jnp.concatenate(
        [prompt, jnp.zeros((b, max_new_tokens), jnp.int32)], axis=1
    )
    tokens = jax.lax.dynamic_update_index_in_dim(
        tokens, first, tp, axis=1)

    def body(carry, t):
        tokens, caches, rng = carry
        tok_t = jax.lax.dynamic_index_in_dim(
            tokens, t, axis=1, keepdims=False)
        logits, caches = decode_step(params, cfg, caches, t, tok_t)
        rng, sub = jax.random.split(rng)
        tokens = jax.lax.dynamic_update_index_in_dim(
            tokens, sample(logits, sub), t + 1, axis=1)
        return (tokens, caches, rng), None

    (tokens, _, _), _ = jax.lax.scan(
        body, (tokens, caches, rng), jnp.arange(tp, total - 1)
    )
    return tokens


def next_token_loss(logits, tokens):
    """Per-example mean next-token cross entropy; tokens: [B, T]."""
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits, targets
    )
    return per_tok.mean(axis=-1)


def next_token_loss_chunked(params, hidden, tokens, cfg, chunk=512):
    """Next-token xent from :func:`forward_hidden` output WITHOUT a
    [B, T, V] logits tensor: the ln_f + head matmul + softmax-xent run
    per T-chunk under ``jax.checkpoint`` inside a scan, so peak live
    logits are [B, chunk, V] in both directions (the backward
    recomputes each chunk's logits).  Numerically identical (f32
    accumulation) to ``next_token_loss(_head(hidden))``.  Returns the
    per-example mean, matching :func:`next_token_loss`.
    """
    b, t, _ = hidden.shape
    h = hidden[:, :-1]
    targets = tokens[:, 1:]
    n = t - 1
    pad = (-n) % chunk
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
    valid = jnp.arange(n + pad) < n
    nc = (n + pad) // chunk
    h = h.reshape(b, nc, chunk, h.shape[-1]).transpose(1, 0, 2, 3)
    tg = targets.reshape(b, nc, chunk).transpose(1, 0, 2)
    mk = valid.reshape(nc, chunk)

    @jax.checkpoint
    def chunk_sum(h_c, t_c, m_c):
        logits = _head(params, h_c, cfg)              # [B, chunk, V]
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits, t_c
        )
        return (per_tok * m_c[None, :]).sum(axis=-1)  # [B]

    def body(acc, xs):
        return acc + chunk_sum(*xs), None

    total, _ = jax.lax.scan(
        body, jnp.zeros((b,), jnp.float32), (h, tg, mk)
    )
    return total / n


# -- zoo contract -------------------------------------------------------------


def model_spec(vocab_size=32000, dim=512, num_heads=8, num_layers=4,
               seq_len=512, learning_rate=3e-4, mesh=None, dtype="bfloat16",
               pipeline_microbatches=0, moe_experts=0, moe_top_k=2,
               moe_aux_weight=0.01, remat=False, attention_impl="ring",
               window=0, xent_chunk=0, num_kv_heads=0):
    """Zoo entry for the flagship LM.

    ``remat`` (False | True | "dots" | "attn"), ``attention_impl``
    ("ring" | "ulysses"), ``window`` (sliding-window causal, 0 = full),
    and ``num_kv_heads`` (grouped-query attention: 0 = MHA, G > 0
    shares each K/V head across num_heads/G query heads) pass through
    to :class:`TransformerConfig`.  ``xent_chunk`` > 0 computes the
    loss via :func:`next_token_loss_chunked` — no [B, T, V] logits
    tensor, the memory-lean path for large vocab x seq (numerically
    identical, tested).
    """
    cfg = TransformerConfig(
        vocab_size=vocab_size, dim=dim, num_heads=num_heads,
        num_layers=num_layers, max_seq_len=seq_len, dtype=dtype,
        moe_experts=moe_experts, moe_top_k=moe_top_k,
        moe_aux_weight=moe_aux_weight, remat=remat,
        attention_impl=attention_impl, window=window,
        num_kv_heads=num_kv_heads,
    )
    cfg.kv_heads  # validate num_heads % num_kv_heads at spec build
    pipelined = (
        pipeline_microbatches > 0
        and mesh is not None
        and mesh.shape.get("pp", 1) > 1
        and mesh.shape.get("sp", 1) == 1
    )
    if not (
        remat in (False, True, "dots", "attn")
    ):
        # CLI model_params arrive as strings; normalize the booleans and
        # reject typos instead of silently enabling full remat (any
        # truthy non-keyword string would take the jax.checkpoint
        # branch).
        normalized = {"false": False, "true": True,
                      "dots": "dots", "attn": "attn"}.get(
            str(remat).strip().lower())
        if normalized is None:
            raise ValueError(
                "remat must be one of False, True, 'dots', 'attn'; "
                "got %r" % (remat,))
        remat = normalized
        cfg = dataclasses.replace(cfg, remat=remat)
    if pipeline_microbatches > 0 and not pipelined:
        # No mesh, pp=1, or sp>1 (ring attention needs the sequence
        # axis): say so instead of silently ignoring the knob.
        import warnings

        warnings.warn(
            "pipeline_microbatches ignored: pipelining requires a mesh "
            "with pp>1 and sp=1; using the scanned forward",
            stacklevel=2,
        )

    def init_fn(rng):
        params = init_params(rng, cfg)
        if mesh is not None:
            params = shard_params(params, mesh, cfg)
        return params

    def apply_fn(params, tokens, train):
        if pipelined:
            if xent_chunk and train:
                hidden, aux = forward_pipelined(
                    params, tokens, cfg, mesh, pipeline_microbatches,
                    remat=bool(cfg.remat), return_aux=True,
                    return_hidden=True,
                )
                return ("hidden", hidden, aux, params)
            return forward_pipelined(
                params, tokens, cfg, mesh, pipeline_microbatches,
                remat=bool(cfg.remat),
                return_aux=bool(cfg.moe_experts and train),
            )
        if xent_chunk and train:
            # Memory-lean loss path: hand the final hidden states (and
            # the params, for the head matmul inside the chunked loss)
            # to loss_fn instead of materializing [B, T, V] logits.
            hidden, aux = forward_hidden(params, tokens, cfg, mesh=mesh)
            return ("hidden", hidden, aux, params)
        if cfg.moe_experts and train:
            return forward(params, tokens, cfg, mesh=mesh,
                           return_aux=True)
        return forward(params, tokens, cfg, mesh=mesh)

    def loss_fn(outputs, tokens):
        if (
            isinstance(outputs, tuple)
            and len(outputs) == 4
            and outputs[0] == "hidden"
        ):
            _, hidden, aux, params = outputs
            loss = next_token_loss_chunked(
                params, hidden, tokens, cfg, chunk=xent_chunk
            )
            if cfg.moe_experts:
                loss = loss + cfg.moe_aux_weight * aux
            return loss
        if isinstance(outputs, tuple):  # MoE training: (logits, aux)
            logits, aux = outputs
            return (
                next_token_loss(logits, tokens)
                + cfg.moe_aux_weight * aux
            )
        return next_token_loss(outputs, tokens)

    def feed(records):
        toks = np.stack(
            [np.asarray(r[0], dtype=np.int32) for r in records]
        )
        # causal LM: inputs are the labels (shifted inside the loss)
        return toks, toks

    spec = ModelSpec(
        name="transformer_lm",
        init_fn=init_fn,
        apply_fn=apply_fn,
        loss_fn=loss_fn,
        optimizer=optax.adamw(learning_rate, weight_decay=0.01),
        feed=feed,
        eval_metrics_fn=lambda: {
            "nll": metrics.Mean(lambda outputs, labels: outputs)
        },
    )
    spec.config = cfg
    return spec


def export_generate(export_dir, params, cfg, max_new_tokens,
                    prompt_len, model_name="lm", temperature=0.0,
                    **export_kwargs):
    """Export GENERATION itself as a servable: the whole batched
    prefill + KV-cache decode loop compiles into the StableHLO
    artifact, so a plain servable host (``elasticdl-tpu serve``, or
    anything that deserializes StableHLO) serves token generation over
    ``:predict`` — prompt ids in, prompt+generated ids out — with no
    model code, no generation loop, no LoRA code (pass merged params)
    on the serving side.

    Static shapes rule the export: ``prompt_len`` and
    ``max_new_tokens`` are fixed per export (export several prompt
    lengths side by side if clients vary); the BATCH stays polymorphic
    like every servable.

    ``temperature`` > 0 exports a SAMPLING servable: the input becomes
    the dict {"prompt": [B, Tp] int32, "seed": [] int32} — the
    per-request seed folds into the PRNG key inside the artifact, so
    repeated requests with different seeds draw different
    continuations and equal seeds reproduce exactly.
    """
    from elasticdl_tpu.serving.export import export_servable

    if prompt_len + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            "prompt_len %d + max_new_tokens %d exceeds max_seq_len %d"
            % (prompt_len, max_new_tokens, cfg.max_seq_len))
    if temperature < 0:
        # A typo'd sign would silently ship a GREEDY artifact here
        # while generate() itself would sample an inverted
        # distribution — reject instead of exporting either surprise.
        raise ValueError("temperature must be >= 0, got %r"
                         % (temperature,))
    if temperature > 0:
        def serve_fn(p, inputs):
            rng = jax.random.PRNGKey(inputs["seed"].astype(jnp.uint32))
            return generate(
                p, cfg, inputs["prompt"],
                max_new_tokens=max_new_tokens,
                temperature=temperature, rng=rng)

        example = {"prompt": np.zeros((1, prompt_len), np.int32),
                   "seed": np.int32(0)}
    else:
        serve_fn = lambda p, prompt: generate(
            p, cfg, prompt, max_new_tokens=max_new_tokens)
        example = np.zeros((1, prompt_len), np.int32)
    return export_servable(
        export_dir,
        serve_fn,
        params,
        example,
        model_name=model_name,
        **export_kwargs,
    )
