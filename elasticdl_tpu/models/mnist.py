"""MNIST zoo model — the minimum end-to-end slice.

Counterpart of the reference's model_zoo/mnist/mnist_functional_api.py:21-103
(custom_model/loss/optimizer/feed/eval_metrics_fn contract), built as a
small conv net in flax.linen with an optax optimizer.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.utils import metrics


class MnistCNN(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        x = x.reshape((x.shape[0], 28, 28, 1))
        x = nn.Conv(32, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def feed(records):
    xs = np.stack([np.asarray(r[0], dtype=np.float32) for r in records])
    ys = np.asarray([int(r[1]) for r in records], dtype=np.int32)
    return xs / 255.0 if xs.max() > 1.5 else xs, ys


def model_spec(learning_rate=1e-3):
    model = MnistCNN()

    def init_fn(rng):
        return model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]

    def apply_fn(params, x, train):
        return model.apply({"params": params}, x, train=train)

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        )

    return ModelSpec(
        name="mnist",
        init_fn=init_fn,
        apply_fn=apply_fn,
        loss_fn=loss_fn,
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {"accuracy": metrics.Accuracy()},
    )


def synthetic_data(n=512, seed=0):
    """Deterministic learnable synthetic digits for tests/benchmarks."""
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 10, size=n).astype(np.int32)
    xs = rng.rand(n, 28, 28).astype(np.float32) * 0.1
    for i in range(n):
        digit = ys[i]
        xs[i, 2 + digit : 6 + digit, 4:24] += 0.9  # class-dependent band
    return xs, ys
