"""Elastic PyTorch training loop — parity with the reference's
model_zoo/mnist/mnist_pytorch.py:32-120 pattern: a stock torch loop made
elastic by (a) an ElasticDataset that pulls master-assigned record
indices and (b) the controller's elastic_run wrapper reporting batch
completion.  Torch runs on CPU here; the framework's control plane is
framework-agnostic — this is the "wrap your own loop" API surface.
"""

import numpy as np

from elasticdl_tpu.api.controller import ElasticCollectiveController
from elasticdl_tpu.api.dataset import ElasticDataset
from elasticdl_tpu.models import mnist as mnist_zoo
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def build_torch_model():
    import torch.nn as nn

    return nn.Sequential(
        nn.Flatten(),
        nn.Linear(28 * 28, 128),
        nn.ReLU(),
        nn.Linear(128, 10),
    )


def train(master_client, n_records=512, batch_size=32, lr=1e-2):
    """Returns (final_loss, batches_run)."""
    import torch
    import torch.nn.functional as F

    xs, ys = mnist_zoo.synthetic_data(n=n_records)
    source = [(xs[i], ys[i]) for i in range(len(ys))]
    dataset = ElasticDataset(source, master_client,
                             batch_size=batch_size)
    model = build_torch_model()
    optimizer = torch.optim.Adam(model.parameters(), lr=lr)
    controller = ElasticCollectiveController(
        master_client, trainer=model,
        data_shard_service=dataset.shard_service,
        global_batch_num=1, check_secs=1e9,
    )

    def train_one_batch(batch_x, batch_y):
        optimizer.zero_grad()
        logits = model(batch_x)
        loss = F.cross_entropy(logits, batch_y)
        loss.backward()
        optimizer.step()
        return float(loss.detach())

    elastic_train = controller.elastic_run(train_one_batch)

    losses = []
    batch = []
    try:
        with controller.scope():
            while True:
                try:
                    batch.append(dataset[0])
                except IndexError:
                    break
                if len(batch) == batch_size:
                    bx = torch.tensor(
                        np.stack([b[0] for b in batch])
                    )
                    by = torch.tensor(
                        np.asarray([b[1] for b in batch],
                                   dtype=np.int64)
                    )
                    losses.append(elastic_train(bx, by))
                    batch = []
            if batch:
                bx = torch.tensor(np.stack([b[0] for b in batch]))
                by = torch.tensor(
                    np.asarray([b[1] for b in batch], dtype=np.int64)
                )
                losses.append(elastic_train(bx, by))
    finally:
        dataset.stop()
    logger.info("torch elastic loop done: %d batches", len(losses))
    return (losses[-1] if losses else float("nan")), len(losses)
