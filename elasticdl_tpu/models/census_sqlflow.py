"""Census wide&deep / DNN from a declarative COLUMN clause — the
SQLFlow-codegen analog (model_zoo/census_model_sqlflow parity).

The reference's census_model_sqlflow package is what SQLFlow's code
generator emits for

    SELECT * FROM census_income TO TRAIN WideAndDeepClassifier
    COLUMN EMBEDDING(CONCAT(VOCABULARIZE(workclass),
                            BUCKETIZE(capital_gain, ...), ...) AS group_1, 8),
           ... FOR deep_embeddings
    COLUMN EMBEDDING(group_1, 1), ... FOR wide_embeddings

(census_wide_and_deep.sql; transform graph in feature_configs.py,
transform op vocabulary in transform_ops.py:17-95).  The TPU-native
analog keeps the clause as *data*: ``CLAUSE`` below is the parsed
COLUMN clause — per-feature transforms (vocabularize / hash /
bucketize), CONCAT groups, and per-group EMBEDDING dims — and
``build_groups`` compiles it onto the declarative feature-column
library (preprocessing/feature_column.py), giving each group one
offset id space and one PS-served embedding table.  Swapping CLAUSE
retargets the model to any schema, which is exactly the SQLFlow
contract; the model function itself never changes.

Variants: ``wide_and_deep`` (census_model_sqlflow/wide_and_deep) and
``dnn`` (census_model_sqlflow/dnn — deep embeddings only).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models import mlp
from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.preprocessing import feature_column as fc
from elasticdl_tpu.utils import metrics

# Vocabularies / boundaries the SQLFlow analyzer derives from the data
# (feature_configs.py keeps the census ones inline the same way).
VOCABULARIES = {
    "workclass": ["private", "gov", "self", "none"],
    "marital_status": ["single", "married", "divorced"],
    "relationship": ["own", "spouse", "child"],
    "race": ["race0", "race1", "race2", "race3"],
    "sex": ["m", "f"],
}
BOUNDARIES = {
    "age": [20, 40, 60, 80],
    "capital_gain": [1000, 4000, 6000, 8000],
    "capital_loss": [1000, 2000, 3000],
    "hours_per_week": [10, 20, 30, 40, 50, 60],
}

# The parsed COLUMN clause: group -> list of (op, column) transforms.
# Mirrors census_wide_and_deep.sql's three CONCAT groups verbatim.
CLAUSE = {
    "deep": {
        "group_1": [
            ("vocabularize", "workclass"),
            ("bucketize", "capital_gain"),
            ("bucketize", "capital_loss"),
            ("bucketize", "hours_per_week"),
        ],
        "group_2": [
            ("hash", "education"),
            ("hash", "occupation"),
            ("vocabularize", "marital_status"),
            ("vocabularize", "relationship"),
        ],
        "group_3": [
            ("bucketize", "age"),
            ("hash", "native_country"),
            ("vocabularize", "race"),
            ("vocabularize", "sex"),
        ],
    },
    # The .sql wide clause embeds groups 1 and 2 at dim 1.
    "wide": ["group_1", "group_2"],
}
HASH_BUCKETS = {"education": 30, "occupation": 30, "native_country": 100}


def _leaf_column(op, key):
    if op == "vocabularize":
        return fc.CategoricalVocabColumn(key, VOCABULARIES[key])
    if op == "hash":
        return fc.CategoricalHashColumn(key, HASH_BUCKETS[key])
    if op == "bucketize":
        return fc.BucketizedColumn(key, BOUNDARIES[key])
    raise ValueError("unknown transform op %r" % op)


def build_groups(clause=None):
    """Compile the clause's CONCAT groups into concatenated columns."""
    clause = clause or CLAUSE
    return {
        name: fc.concatenated_categorical_column(
            [_leaf_column(op, key) for op, key in transforms]
        )
        for name, transforms in clause["deep"].items()
    }


def _table(group, role):
    return "census_sqlflow_%s_%s" % (group, role)


def init_params(rng, fields_per_group, embedding_dim,
                hidden=(64, 32)):
    d0 = sum(fields_per_group) * embedding_dim
    params = mlp.mlp_init(rng, [d0] + list(hidden) + [1])
    params["bias"] = jnp.zeros((1,), jnp.float32)
    return params


def make_forward(group_names, wide_groups):
    def forward(params, feats, train):
        deep_parts = []
        for g in group_names:
            t = _table(g, "deep")
            rows = feats["emb__" + t][feats["idx__" + t]]
            deep_parts.append(rows.reshape(rows.shape[0], -1))
        x = jnp.concatenate(deep_parts, axis=-1)
        logit = mlp.mlp_apply(params, x)[:, 0] + params["bias"][0]
        for g in wide_groups:
            t = _table(g, "wide")
            logit = logit + feats["emb__" + t][feats["idx__" + t]][
                ..., 0
            ].sum(axis=1)
        return logit

    return forward


def model_spec(variant="wide_and_deep", embedding_dim=8,
               hidden=(64, 32), learning_rate=1e-3, clause=None,
               column_order=""):
    """``column_order``: comma-separated column names for list-shaped
    rows (SQL/CSV sources); empty for dict-shaped records."""
    clause = clause or CLAUSE
    groups = build_groups(clause)
    group_names = sorted(groups)
    wide_groups = list(clause["wide"]) if variant == "wide_and_deep" \
        else []

    # One PS table per (group, role); wide tables are dim-1 linear
    # weights over the same id space (EMBEDDING(group, 1) in the .sql).
    id_tables = {}
    infos = []
    for g in group_names:
        id_tables[_table(g, "deep")] = groups[g]
        infos.append({"name": _table(g, "deep"), "dim": embedding_dim,
                      "initializer": "uniform"})
    for g in wide_groups:
        id_tables[_table(g, "wide")] = groups[g]
        infos.append({"name": _table(g, "wide"), "dim": 1,
                      "initializer": "zeros"})
    order = [c for c in column_order.split(",") if c] or None
    feed = fc.make_feed([], id_tables, column_order=order)
    fields = [len(groups[g].columns) for g in group_names]

    def init_fn(rng):
        return init_params(rng, fields, embedding_dim, hidden)

    def loss_fn(logits, labels):
        return optax.sigmoid_binary_cross_entropy(
            logits, labels.astype(jnp.float32)
        )

    return ModelSpec(
        name="census_sqlflow_%s" % variant,
        init_fn=init_fn,
        apply_fn=make_forward(group_names, wide_groups),
        loss_fn=loss_fn,
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {
            "auc": metrics.AUC(),
            "accuracy": metrics.BinaryAccuracy(threshold=0.0),
        },
        ps_embedding_infos=infos,
        ps_optimizer=("adam", "learning_rate=%g" % learning_rate),
    )
