"""User-facing embedding layers for zoo models.

The reference exposes `elasticdl.layers.Embedding` + EmbeddingColumn whose
weights live on the PS (embedding.py:20-162, feature_column.py:25-221,
with the lookup machinery in embedding_delegate.py:26-310).  Here the
same capability is two small pieces that compose with the trainer's
``emb__/idx__`` convention:

 - ``Embedding``: declares a PS table and, inside the jitted step, turns
   the trainer-provided rows + indices into dense [B, F, dim] (or
   combined [B, dim]) activations.  Dense ids and ragged
   (padded + mask) inputs both work; combiners match the reference
   (sum / mean / sqrtn).
 - ``embedding_feature_column``: the feature-column-style helper that
   binds a feature name to an Embedding for tabular feeds.

Whether the table actually lives on the PS or on-device is decided by
models/model_handler.py's placement plan — the layer is agnostic.
"""

import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.preprocessing.layers import SparseEmbedding


class Embedding:
    def __init__(self, name, dim, initializer="uniform", combiner=None):
        """combiner None -> [B, F, dim] sequence output;
        'sum'|'mean'|'sqrtn' -> [B, dim] pooled output."""
        self.name = name
        self.dim = dim
        self.initializer = initializer
        self.combiner = combiner
        self._combine = SparseEmbedding(combiner) if combiner else None

    @property
    def info(self):
        """ps_embedding_infos entry for the ModelSpec."""
        return {"name": self.name, "dim": self.dim,
                "initializer": self.initializer}

    # -- feed side ----------------------------------------------------------

    def collect_ids(self, features, ids, mask=None):
        """Register this layer's ids into a feed's feature dict."""
        features.setdefault("__ids__", {})[self.name] = np.asarray(
            ids, np.int64
        )
        if mask is not None:
            features["mask__" + self.name] = np.asarray(mask, np.float32)
        return features

    # -- device side --------------------------------------------------------

    def __call__(self, feats):
        """Inside apply_fn: gather this layer's activations."""
        rows = feats["emb__" + self.name]          # [U, dim] or [V, dim]
        idx = feats["idx__" + self.name]           # [B, F]
        gathered = rows[idx]                       # [B, F, dim]
        if self._combine is None:
            return gathered
        mask = feats.get("mask__" + self.name)
        if mask is None:
            mask = jnp.ones(idx.shape, jnp.float32)
        return self._combine(gathered, mask)


def embedding_feature_column(feature_name, vocab_size, dim,
                             combiner="mean"):
    """Feature-column-style helper: returns an Embedding whose table is
    named after the feature (reference EmbeddingColumn parity)."""
    layer = Embedding("col__" + feature_name, dim, combiner=combiner)
    layer.vocab_size = vocab_size
    return layer
