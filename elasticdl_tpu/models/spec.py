"""The model-zoo contract.

The reference selects user model components by name from a zoo module
(elasticdl/python/common/model_utils.py:135-192: model/loss/optimizer/feed/
eval_metrics_fn).  Here the contract is a single ``ModelSpec`` value the
module builds via an exported ``model_spec(**kwargs)`` function — pure
functions + pytrees, so every field composes with jit/grad/shard_map.

Conventions:
 - ``loss_fn(outputs, labels)`` returns a *per-example* loss vector; the
   trainer applies padding masks and reduces.  (Static batch shapes for XLA:
   partial minibatches are padded, never shape-changed.)
 - ``feed(records)`` turns a list of reader records into a tuple of ndarrays
   ``(inputs..., labels)`` forming one batch.
"""

import dataclasses
import importlib
import typing


@dataclasses.dataclass
class ModelSpec:
    name: str
    init_fn: typing.Callable        # rng -> params pytree
    apply_fn: typing.Callable       # (params, inputs, train) -> outputs
    loss_fn: typing.Callable        # (outputs, labels) -> per-example loss
    optimizer: typing.Any           # optax.GradientTransformation
    feed: typing.Callable           # [records] -> (inputs, labels) ndarrays
    eval_metrics_fn: typing.Callable = None  # () -> {name: Metric}
    prediction_outputs_processor: typing.Any = None
    callbacks: list = dataclasses.field(default_factory=list)
    # Optional: names of embedding tables served by the parameter server
    # (the sparse path); empty for pure dense models.
    ps_embedding_infos: list = dataclasses.field(default_factory=list)
    # PS-side optimizer as (opt_type, opt_args) flag strings — the analog
    # of the reference's Keras-optimizer -> Go-PS-flags mapping
    # (model_utils.py:227-254).
    ps_optimizer: tuple = ("sgd", "learning_rate=0.1")


def load_model_spec(module_name, model_params="", **kwargs):
    """Import a zoo module and build its ModelSpec.

    ``module_name`` may be a short zoo name ("mnist") or a full dotted
    path; ``model_params`` is a "k=v;k=v" string merged into kwargs
    (ints/floats parsed; the reference's --model_def/--model_params
    mechanism, model_utils.py:135-192).
    """
    if model_params:
        from elasticdl_tpu.utils.args import parse_opt_args

        for key, value in parse_opt_args(model_params).items():
            if isinstance(value, float) and value.is_integer():
                value = int(value)
            kwargs.setdefault(key, value)
    if "." not in module_name:
        module_name = "elasticdl_tpu.models." + module_name
    module = importlib.import_module(module_name)
    if not hasattr(module, "model_spec"):
        raise ValueError(
            "%s does not export model_spec(**kwargs)" % module_name
        )
    return module.model_spec(**kwargs)
