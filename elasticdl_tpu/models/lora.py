"""LoRA fine-tuning for the flagship LM (beyond the reference).

Low-Rank Adaptation (Hu et al. 2021, arXiv:2106.09685): freeze the
pretrained weights W and train a rank-r update W + (alpha/r) * A @ B
per target matrix.  TPU-first design decisions:

 - **Merge-at-forward**: the adapted weights are materialized as
   W_eff = W + scale * A @ B (one [L, in, r] x [L, r, out] einsum over
   the stacked-layer axis) and handed to the UNCHANGED transformer
   forward.  XLA fuses the rank-r update into the surrounding graph;
   autodiff routes gradients to A and B through W_eff, and the
   optimizer mask discards the base gradient — no per-call-site
   adapter plumbing inside the scanned block, so every attention
   variant (ring/Ulysses, window, GQA, MoE, remat, pipelined) works
   under LoRA for free.
 - **Frozen base via optax.multi_transform**: base leaves get
   ``set_to_zero`` (no optimizer moments allocated — Adam moments for
   a frozen 436M base would cost 3.5 GB), adapter leaves get AdamW.
 - **Merged export**: ``merged_params`` folds the adapters back into
   plain transformer params, so the servable / generate() path is a
   VANILLA transformer — serving needs no LoRA code at all.

Zoo usage::

    elasticdl-tpu train --model_zoo lora \
      --model_params "rank=8;alpha=16;base_export=/path/to/export"

``base_export`` points at a servable/weights export of the base LM
(models/callbacks.load_export layout) for the fine-tuning story:
pretrain -> export -> LoRA-adapt -> merged servable.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models import transformer as tfm
from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")


def _target_shapes(base_layers, targets):
    """{target: (in_dim, out_dim)} for each adapted [L, in, out] W."""
    shapes = {}
    for t in targets:
        if t not in base_layers:
            raise ValueError(
                "unknown LoRA target %r; this architecture has: %s"
                % (t, ", ".join(sorted(base_layers))))
        w = base_layers[t]
        if w.ndim != 3:
            raise ValueError(
                "LoRA target %r has rank-%d weights; only stacked "
                "[L, in, out] matrices are adaptable" % (t, w.ndim))
        shapes[t] = (w.shape[1], w.shape[2])
    return shapes


def init_lora(rng, base_layers, targets, rank):
    """A ~ N(0, 1/r) (scaled), B = 0 — the standard init: the delta
    starts at exactly zero, so step 0 reproduces the base model."""
    L = next(iter(base_layers.values())).shape[0]
    lora = {}
    for i, (t, (d_in, d_out)) in enumerate(
        sorted(_target_shapes(base_layers, targets).items())
    ):
        key = jax.random.fold_in(rng, i)
        lora[t] = {
            "A": jax.random.normal(key, (L, d_in, rank),
                                   jnp.float32) / np.sqrt(rank),
            "B": jnp.zeros((L, rank, d_out), jnp.float32),
        }
    return lora


def merge_layers(base_layers, lora, scaling):
    """base layers dict -> same dict with W_eff on adapted targets."""
    merged = dict(base_layers)
    for t, ab in lora.items():
        delta = jnp.einsum("lir,lro->lio", ab["A"], ab["B"])
        merged[t] = base_layers[t] + scaling * delta.astype(
            base_layers[t].dtype)
    return merged


def merged_params(params, scaling):
    """Fold adapters into plain transformer params (serving/export).

    ``params`` is this spec's {"base": ..., "lora": ...} tree and
    ``scaling`` the spec's alpha/rank (``spec.lora["scaling"]`` —
    REQUIRED: a defaulted value would silently mis-scale the merge for
    any non-default rank/alpha).  Returns the base tree with W_eff in
    place — loadable by every vanilla transformer entrypoint (forward,
    generate, servable export)."""
    base = dict(params["base"])
    base["layers"] = merge_layers(
        params["base"]["layers"], params["lora"], scaling)
    return base


def _load_base_export(base_export, init_params):
    """Replace freshly-initialized base params with an export's
    weights (models/callbacks.load_export layout), matched by flat
    name."""
    from elasticdl_tpu.models.callbacks import load_export
    from elasticdl_tpu.utils.pytree import (
        flatten_with_names,
        unflatten_from_names,
    )

    dense, _ = load_export(base_export)
    named, _ = flatten_with_names(init_params)
    missing = sorted(set(named) - set(dense))
    if missing:
        raise ValueError(
            "base export %s lacks %d parameters (e.g. %s) — wrong "
            "architecture kwargs?" % (base_export, len(missing),
                                      missing[:3]))
    return unflatten_from_names(init_params, dense)


def model_spec(rank=8, alpha=16.0, lora_targets=None, base_export="",
               learning_rate=1e-4, train_norms=False, **lm_kwargs):
    """Zoo entry: the flagship LM with LoRA adapters.

    ``lora_targets``: comma-joined target names (default the four
    attention projections; MLP matrices w_gate/w_up/w_down are valid
    too).  ``base_export``: directory of a base-LM export to fine-tune
    from (fresh random base otherwise — useful for tests).
    ``train_norms``: also train the (tiny) norm scales, a common LoRA+
    variant.  Remaining kwargs go to transformer.model_spec.
    """
    lm_kwargs.setdefault("learning_rate", learning_rate)
    base_spec = tfm.model_spec(**lm_kwargs)
    cfg = base_spec.config
    if isinstance(lora_targets, str):
        targets = tuple(
            t.strip() for t in lora_targets.split(",") if t.strip())
    else:
        targets = tuple(lora_targets or DEFAULT_TARGETS)
    rank = int(rank)
    scaling = float(alpha) / rank

    def init_fn(rng):
        base = base_spec.init_fn(rng)
        if base_export:
            base = _load_base_export(base_export, base)
        lora = init_lora(jax.random.fold_in(rng, 999),
                         base["layers"], targets, rank)
        n_adapter = sum(
            int(np.prod(np.shape(leaf)))
            for leaf in jax.tree_util.tree_leaves(lora))
        n_base = sum(
            int(np.prod(np.shape(leaf)))
            for leaf in jax.tree_util.tree_leaves(base))
        logger.info(
            "LoRA r=%d over %s: %d trainable / %d frozen params "
            "(%.2f%%)", rank, ",".join(sorted(targets)), n_adapter,
            n_base, 100.0 * n_adapter / max(1, n_base))
        return {"base": base, "lora": lora}

    def apply_fn(params, tokens, train):
        return base_spec.apply_fn(
            merged_params(params, scaling=scaling), tokens, train)

    def _labels(params):
        base_labels = jax.tree_util.tree_map_with_path(
            lambda path, _leaf: (
                "train_norm"
                if train_norms and any(
                    getattr(k, "key", "") in ("ln1", "ln2", "ln_f")
                    for k in path
                )
                else "freeze"
            ),
            params["base"],
        )
        lora_labels = jax.tree_util.tree_map(
            lambda _leaf: "train", params["lora"])
        return {"base": base_labels, "lora": lora_labels}

    optimizer = optax.multi_transform(
        {
            # Adapter weight decay regularizes the DELTA — the
            # standard LoRA choice.
            "train": optax.adamw(lm_kwargs["learning_rate"],
                                 weight_decay=0.01),
            # Norm scales are trained WITHOUT decay (decay would pull
            # the 1.0-initialized RMSNorm scales toward zero — norms
            # are conventionally excluded from weight decay).
            "train_norm": optax.adam(lm_kwargs["learning_rate"]),
            "freeze": optax.set_to_zero(),
        },
        _labels,
    )

    spec = ModelSpec(
        name="transformer_lm_lora",
        init_fn=init_fn,
        apply_fn=apply_fn,
        loss_fn=base_spec.loss_fn,
        optimizer=optimizer,
        feed=base_spec.feed,
        eval_metrics_fn=base_spec.eval_metrics_fn,
    )
    spec.config = dataclasses.replace(cfg)
    spec.lora = {"rank": rank, "scaling": scaling, "targets": targets}
    return spec
