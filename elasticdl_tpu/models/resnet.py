"""ResNet family — the reference's headline benchmark model
(model_zoo/cifar10 and model_zoo/resnet50_subclass; perf baselines in
docs/benchmark/ftlib_benchmark.md).

TPU-first choices: NHWC layout (XLA's native conv layout on TPU),
GroupNorm instead of BatchNorm (no cross-replica batch-stats sync, no
mutable state threading through the jitted step, identical FLOP profile),
and bf16-friendly initializers.  Compute dtype is controlled by the
trainer (use_bf16_compute) so the MXU runs in bfloat16 with float32 params.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.ops.group_norm import fused_group_norm
from elasticdl_tpu.utils import metrics


class GroupNorm(nn.Module):
    """GroupNorm(+ReLU) on the fused Pallas kernel (ops/group_norm.py);
    param names/shapes match flax.linen.GroupNorm so checkpoints are
    interchangeable with the un-fused module."""

    num_groups: int
    relu: bool = False

    @nn.compact
    def __call__(self, x):
        channels = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (channels,))
        bias = self.param("bias", nn.initializers.zeros, (channels,))
        return fused_group_norm(x, scale, bias, self.num_groups,
                                relu=self.relu)


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    groups: int = 32

    @nn.compact
    def __call__(self, x):
        def gn(channels, relu=False):
            # group count that always divides the channel count
            return GroupNorm(
                num_groups=int(np.gcd(self.groups, channels)),
                relu=relu,
            )

        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False)(x)
        y = gn(self.features, relu=True)(y)
        y = nn.Conv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            padding="SAME", use_bias=False,
        )(y)
        y = gn(self.features, relu=True)(y)
        out_features = self.features * 4
        y = nn.Conv(out_features, (1, 1), use_bias=False)(y)
        y = gn(out_features)(y)
        if residual.shape[-1] != out_features or self.strides != 1:
            residual = nn.Conv(
                out_features, (1, 1),
                strides=(self.strides, self.strides), use_bias=False,
            )(residual)
            residual = gn(out_features)(residual)
        return nn.relu(y + residual)


def space_to_depth(x, block=2):
    """[B, H, W, C] -> [B, H/b, W/b, C*b*b] (TPU input-pipeline trick:
    the stem conv then runs on b*b*C channels instead of C=3, which the
    MXU tiles far better than a 3-channel 7x7)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, c * block * block)


class ResNet(nn.Module):
    stage_sizes: tuple = (3, 4, 6, 3)   # ResNet-50
    num_classes: int = 1000
    width: int = 64
    cifar_stem: bool = False            # 3x3/1 stem for 32x32 inputs
    # Space-to-depth stem: fold 2x2 spatial blocks into channels BEFORE
    # the stem conv, replacing the 7x7/2 conv on C=3 (an MXU-hostile
    # shape — 3 input channels leave >90% of the systolic array's
    # contraction dim idle) with a 4x4/1 conv on C=12 over the halved
    # grid.  Same output shape (112x112x64 into the pool) and receptive
    # field class; standard on TPU (MLPerf ResNet), trains from scratch.
    s2d_stem: bool = False

    @nn.compact
    def __call__(self, x, train=True):
        if self.cifar_stem:
            x = nn.Conv(self.width, (3, 3), padding="SAME",
                        use_bias=False)(x)
        elif self.s2d_stem:
            x = space_to_depth(x, 2)       # [B, 112, 112, 12]
            # stride 1 on the s2d grid == stride 2 on the original;
            # the usual 3x3/2 max pool below still takes 112 -> 56
            x = nn.Conv(self.width, (4, 4), padding="SAME",
                        use_bias=False)(x)
        else:
            x = nn.Conv(self.width, (7, 7), strides=(2, 2),
                        padding=[(3, 3), (3, 3)], use_bias=False)(x)
        x = GroupNorm(num_groups=int(np.gcd(32, self.width)),
                      relu=True)(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, blocks in enumerate(self.stage_sizes):
            features = self.width * (2 ** stage)
            for block in range(blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = Bottleneck(features=features, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes,
                        kernel_init=nn.initializers.zeros_init())(x)


def _make_spec(model, name, input_shape, learning_rate, momentum=0.9):
    def init_fn(rng):
        return model.init(rng, jnp.zeros((1,) + input_shape))["params"]

    def apply_fn(params, x, train):
        return model.apply({"params": params}, x, train=train)

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        )

    def feed(records):
        xs = np.stack(
            [np.asarray(r[0], dtype=np.float32) for r in records]
        )
        ys = np.asarray([int(r[1]) for r in records], dtype=np.int32)
        return xs, ys

    return ModelSpec(
        name=name,
        init_fn=init_fn,
        apply_fn=apply_fn,
        loss_fn=loss_fn,
        optimizer=optax.sgd(learning_rate, momentum=momentum),
        feed=feed,
        eval_metrics_fn=lambda: {"accuracy": metrics.Accuracy()},
    )


def model_spec(variant="resnet50", num_classes=1000, image_size=224,
               learning_rate=0.1):
    """Zoo entry.  variant: resnet50 | resnet50_cifar10 | resnet18_cifar10."""
    if variant == "resnet50":
        model = ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes)
        return _make_spec(model, "resnet50",
                          (image_size, image_size, 3), learning_rate)
    if variant == "resnet50_s2d":
        model = ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes,
                       s2d_stem=True)
        return _make_spec(model, "resnet50_s2d",
                          (image_size, image_size, 3), learning_rate)
    if variant == "resnet50_cifar10":
        model = ResNet(stage_sizes=(3, 4, 6, 3), num_classes=10,
                       cifar_stem=True)
        return _make_spec(model, "resnet50_cifar10", (32, 32, 3),
                          learning_rate)
    if variant == "resnet_small_cifar10":
        model = ResNet(stage_sizes=(2, 2, 2, 2), num_classes=10,
                       cifar_stem=True)
        return _make_spec(model, "resnet_small_cifar10", (32, 32, 3),
                          learning_rate)
    raise ValueError("unknown resnet variant %r" % variant)
