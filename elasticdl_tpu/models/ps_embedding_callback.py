"""PS embedding lookups INSIDE the jitted step — callback + custom VJP.

SURVEY §7 names this the fiddly hard part: the reference routes
embedding lookups through ``tf.py_function`` and re-wires the tape so
sparse gradients flow back to the parameter server
(elasticdl/python/elasticdl/layers/embedding.py +
embedding_delegate.py:232-281).  The framework's DEFAULT design avoids
the problem entirely (the emb__/idx__ convention: the trainer pulls
rows on the host and feeds them as pure jit inputs).  This module is
the direct JAX analog of the reference mechanism for when the lookup
must live inside the compiled step:

 - forward: ``jax.pure_callback`` pulls rows from the PS mid-step
   (shape-static: [B] ids -> [B, dim] f32);
 - backward: a ``custom_vjp`` whose bwd rule fires an ORDERED
   ``io_callback`` pushing the sparse gradient straight to the PS (the
   async-SGD push — duplicate ids merge server-side), and returns a
   float0 cotangent for the integer ids;
 - **the table handle**: reverse AD only evaluates a VJP on paths that
   reach a differentiated input, and PS rows depend on no local
   parameter — the exact gap TF's tape bridges with
   ``tape.watch(embedding_output)``.  The JAX-idiomatic bridge: the
   table is represented IN the param pytree by a scalar ``handle``
   (``PSEmbedding.handle``, value 0.0, gradient 0.0 — optimizers
   no-op on it), and ``lookup(ids, handle)`` threads it through, so
   the output cotangent must flow through the lookup and the bwd push
   fires.

Trade-offs vs the default design (documented, measured by the data
plane bench): a host round-trip inside every step (the reference pays
the same via py_function) and push-on-backward semantics (the PS
applies the update immediately — async mode; in sync mode pair it
with grads_to_wait as usual).  Use the default host-pulled design
unless the table cannot be staged per-batch.
"""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class PSEmbedding:
    """One PS-backed table, usable inside jitted train/eval steps.

    ``lookup(ids)`` is differentiable: its backward pushes the sparse
    gradient to the PS.  ``version_fn`` supplies the gradient version
    for staleness handling (defaults to 0 — pure async)."""

    def __init__(self, ps_client, table, dim, learning_rate=0.0,
                 version_fn=None):
        self._ps = ps_client
        self._table = table
        self._dim = int(dim)
        self._learning_rate = learning_rate
        self._version_fn = version_fn or (lambda: 0)

        def _pull(ids):
            rows = self._ps.pull_embedding_vectors(
                self._table, np.asarray(ids, np.int64).ravel())
            return np.asarray(rows, np.float32).reshape(
                ids.shape + (self._dim,))

        def _push(ids, grads):
            version = int(self._version_fn())
            accepted, server_version = self._ps.push_gradients(
                {},
                {self._table: (
                    np.asarray(grads, np.float32).reshape(
                        -1, self._dim),
                    np.asarray(ids, np.int64).ravel(),
                )},
                version=version,
                learning_rate=self._learning_rate,
            )
            if not accepted:
                # Sync-mode staleness reject: the minibatch's table
                # update is DROPPED (the dense path re-pulls and
                # retries; a backward-pass push has no retry point) —
                # at least say so instead of silently not learning.
                logger.warning(
                    "PS rejected embedding push for %r (grad version "
                    "%d vs server %s); table update dropped",
                    self._table, version, server_version)
            return np.zeros((), np.int32)  # io_callback token

        def _call_pull(ids):
            return jax.pure_callback(
                _pull,
                jax.ShapeDtypeStruct(ids.shape + (self._dim,),
                                     jnp.float32),
                ids,
            )

        @jax.custom_vjp
        def lookup(ids, handle):
            del handle  # differentiation hook only (see module doc)
            return _call_pull(ids)

        def fwd(ids, handle):
            del handle
            return _call_pull(ids), ids

        def bwd(ids, g):
            # Ordered: pushes must not be elided or reordered — they
            # ARE the training update for this table.
            jax.experimental.io_callback(
                _push, jax.ShapeDtypeStruct((), jnp.int32), ids, g,
                ordered=True,
            )
            # Integer ids take a float0 cotangent; the handle's
            # cotangent is zero (the "weights" live on the PS).
            return (np.zeros(ids.shape, jax.dtypes.float0),
                    jnp.zeros((), jnp.float32))

        lookup.defvjp(fwd, bwd)
        self._lookup = lookup

    @property
    def handle(self):
        """Put this in the param pytree and thread it into
        ``__call__``: it is what routes the loss cotangent through the
        lookup so the backward push fires."""
        return jnp.zeros((), jnp.float32)

    def __call__(self, ids, handle):
        return self._lookup(jnp.asarray(ids), handle)
