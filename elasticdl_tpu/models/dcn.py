"""Deep & Cross Network (DCN) — dac_ctr zoo parity (reference
model_zoo/dac_ctr includes DCN alongside DeepFM/xDeepFM/wide-deep).

Same PS feature convention as deepfm.py: one shared factor table served by
the parameter server; cross layers run on-device inside the jitted step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.models import deepfm as _ctr
from elasticdl_tpu.utils import metrics

EMB_TABLE = "dcn_embedding"


def init_params(rng, num_dense, num_fields, embedding_dim,
                num_cross_layers=3, hidden=(128, 64)):
    d0 = num_fields * embedding_dim + num_dense
    keys = jax.random.split(rng, num_cross_layers + len(hidden) + 2)
    params = {}
    for i in range(num_cross_layers):
        params["cross_w%d" % i] = (
            jax.random.normal(keys[i], (d0,)) * (1.0 / np.sqrt(d0))
        ).astype(jnp.float32)
        params["cross_b%d" % i] = jnp.zeros((d0,), jnp.float32)
    sizes = [d0] + list(hidden)
    for i in range(len(hidden)):
        params["deep_w%d" % i] = (
            jax.random.normal(keys[num_cross_layers + i],
                              (sizes[i], sizes[i + 1]))
            * np.sqrt(2.0 / sizes[i])
        ).astype(jnp.float32)
        params["deep_b%d" % i] = jnp.zeros((sizes[i + 1],), jnp.float32)
    params["out_w"] = (
        jax.random.normal(keys[-1], (d0 + sizes[-1], 1)) * 0.01
    ).astype(jnp.float32)
    params["out_b"] = jnp.zeros((1,), jnp.float32)
    return params


def forward(params, feats, train):
    v = feats["emb__" + EMB_TABLE][feats["idx__" + EMB_TABLE]]  # [B,F,k]
    x0 = v.reshape(v.shape[0], -1)
    if feats.get("dense") is not None:
        x0 = jnp.concatenate([x0, feats["dense"]], axis=-1)
    # cross tower: x_{l+1} = x0 * <w_l, x_l> + b_l + x_l
    x = x0
    n_cross = sum(1 for k in params if k.startswith("cross_w"))
    for i in range(n_cross):
        xw = x @ params["cross_w%d" % i]                      # [B]
        x = x0 * xw[:, None] + params["cross_b%d" % i] + x
    # deep tower
    h = x0
    n_deep = sum(1 for k in params if k.startswith("deep_w"))
    for i in range(n_deep):
        h = jax.nn.relu(h @ params["deep_w%d" % i]
                        + params["deep_b%d" % i])
    out = jnp.concatenate([x, h], axis=-1) @ params["out_w"]
    return out[:, 0] + params["out_b"][0]


def model_spec(num_dense=4, num_fields=8, vocab_size=10000,
               embedding_dim=8, num_cross_layers=3, hidden=(128, 64),
               learning_rate=1e-3):
    def init_fn(rng):
        return init_params(rng, num_dense, num_fields, embedding_dim,
                           num_cross_layers, hidden)

    def loss_fn(logits, labels):
        return optax.sigmoid_binary_cross_entropy(
            logits, labels.astype(jnp.float32)
        )

    def feed(records):
        dense = np.stack([np.asarray(r[0], np.float32) for r in records])
        ids = np.stack([np.asarray(r[1], np.int64) for r in records])
        labels = np.asarray([int(r[2]) for r in records], np.int32)
        return {"dense": dense, "__ids__": {EMB_TABLE: ids}}, labels

    return ModelSpec(
        name="dcn",
        init_fn=init_fn,
        apply_fn=lambda p, f, t: forward(p, f, t),
        loss_fn=loss_fn,
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {
            "auc": metrics.AUC(),
            "accuracy": metrics.BinaryAccuracy(threshold=0.0),
        },
        ps_embedding_infos=[
            {"name": EMB_TABLE, "dim": embedding_dim,
             "initializer": "uniform"},
        ],
        ps_optimizer=("adam", "learning_rate=%g" % learning_rate),
    )


synthetic_data = _ctr.synthetic_data
