"""DeepFM with PS-served embeddings — the sparse CTR path.

Counterpart of the reference's model_zoo/deepfm_functional_api and
dac_ctr zoo (deepfm_edl_embedding uses PS-backed elasticdl.layers.Embedding,
SURVEY.md §2.11).  Two PS tables: second-order factor embeddings [V, k] and
first-order linear weights [V, 1]; the dense MLP weights also live on the
PS (pushed/pulled by the ParameterServerTrainer).

Feature convention: categorical ids are pre-offset into one vocab space
(the reference's ConcatenateWithOffset pattern); features arrive as
  {"dense": [B, Dn] float, "__ids__": {"deepfm_embedding": [B, F],
                                       "deepfm_linear": [B, F]}}
and the trainer injects  emb__<table> ([U, dim] pulled rows) and
idx__<table> ([B, F] gather indices).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.utils import metrics

EMB_TABLE = "deepfm_embedding"
LIN_TABLE = "deepfm_linear"


def init_params(rng, num_dense, num_fields, embedding_dim,
                hidden=(128, 64)):
    sizes = [num_fields * embedding_dim + num_dense] + list(hidden) + [1]
    keys = jax.random.split(rng, len(sizes))
    params = {"bias": jnp.zeros((1,), jnp.float32)}
    for i in range(len(sizes) - 1):
        params["w%d" % i] = (
            jax.random.normal(keys[i], (sizes[i], sizes[i + 1]))
            * np.sqrt(2.0 / sizes[i])
        ).astype(jnp.float32)
        params["b%d" % i] = jnp.zeros((sizes[i + 1],), jnp.float32)
    return params


def forward(params, feats, train):
    emb_rows = feats["emb__" + EMB_TABLE]        # [U, k]
    emb_idx = feats["idx__" + EMB_TABLE]         # [B, F]
    lin_rows = feats["emb__" + LIN_TABLE]        # [U, 1]
    lin_idx = feats["idx__" + LIN_TABLE]         # [B, F]
    dense = feats.get("dense")

    v = emb_rows[emb_idx]                        # [B, F, k]
    # first-order term
    first = lin_rows[lin_idx][..., 0].sum(axis=1)            # [B]
    # FM second-order: 0.5 * ((sum v)^2 - sum v^2)
    sum_v = v.sum(axis=1)                                    # [B, k]
    second = 0.5 * (
        jnp.square(sum_v) - jnp.square(v).sum(axis=1)
    ).sum(axis=-1)                                           # [B]
    # deep part
    flat = v.reshape(v.shape[0], -1)
    x = jnp.concatenate([flat, dense], axis=-1) if dense is not None \
        else flat
    n_layers = sum(1 for k in params if k.startswith("w"))
    for i in range(n_layers):
        x = x @ params["w%d" % i] + params["b%d" % i]
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    deep = x[:, 0]                                           # [B]
    return first + second + deep + params["bias"][0]


def model_spec(num_dense=4, num_fields=8, vocab_size=10000,
               embedding_dim=8, learning_rate=1e-3, hidden=(128, 64)):
    def init_fn(rng):
        return init_params(rng, num_dense, num_fields, embedding_dim,
                           hidden)

    def loss_fn(logits, labels):
        return optax.sigmoid_binary_cross_entropy(
            logits, labels.astype(jnp.float32)
        )

    def feed(records):
        dense = np.stack(
            [np.asarray(r[0], np.float32) for r in records]
        )
        ids = np.stack([np.asarray(r[1], np.int64) for r in records])
        labels = np.asarray([int(r[2]) for r in records], np.int32)
        return (
            {
                "dense": dense,
                "__ids__": {EMB_TABLE: ids, LIN_TABLE: ids},
            },
            labels,
        )

    return ModelSpec(
        name="deepfm",
        init_fn=init_fn,
        apply_fn=lambda params, feats, train: forward(params, feats,
                                                      train),
        loss_fn=loss_fn,
        optimizer=optax.adam(learning_rate),
        feed=feed,
        eval_metrics_fn=lambda: {
            "auc": metrics.AUC(),
            "accuracy": metrics.BinaryAccuracy(threshold=0.0),
        },
        ps_embedding_infos=[
            {"name": EMB_TABLE, "dim": embedding_dim,
             "initializer": "uniform"},
            {"name": LIN_TABLE, "dim": 1, "initializer": "zeros"},
        ],
        ps_optimizer=("adam", "learning_rate=%g" % learning_rate),
    )


def synthetic_data(n=1024, num_dense=4, num_fields=8, vocab_size=10000,
                   seed=0):
    """Learnable synthetic CTR data: the label depends on a hidden weight
    per category id, so embeddings must be learned for AUC > 0.5."""
    rng = np.random.RandomState(seed)
    hidden_w = rng.randn(vocab_size) * 0.5
    dense = rng.rand(n, num_dense).astype(np.float32)
    field_offsets = (
        np.arange(num_fields) * (vocab_size // num_fields)
    )
    raw = rng.randint(0, vocab_size // num_fields, size=(n, num_fields))
    ids = (raw + field_offsets[None, :]).astype(np.int64)
    score = hidden_w[ids].sum(axis=1) + dense.sum(axis=1) - num_dense / 2
    labels = (score > 0).astype(np.int32)
    return dense, ids, labels
