"""Embedding placement planning — the ModelHandler analog.

The reference swaps ``tf.keras.layers.Embedding`` for PS-backed layers
when a table exceeds 2 MB (model_handler.py:98-102, threshold at
EMBEDDING_SIZE_THRESHOLD_IN_BYTES) and reverses the transform for export.
Here the same decision routes each declared embedding table either to the
parameter server (bigger than the threshold / HBM budget) or to a
device-resident parameter (small tables train fastest as plain params
inside the jitted step with the collective path).

``localize_spec`` rewrites a PS-style ModelSpec so chosen tables become
ordinary parameters: the model's forward already consumes
``emb__<table>[idx__<table>]``, so a local table is just the full [V, d]
array passed as ``emb__<table>`` with raw ids as indices — no model code
changes.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# reference: 2 MB threshold (model_handler.py:98-102)
EMBEDDING_PS_THRESHOLD_BYTES = 2 * 1024 * 1024


def plan_embedding_placement(infos, vocab_sizes,
                             threshold_bytes=EMBEDDING_PS_THRESHOLD_BYTES):
    """Split table names into {"ps": [...], "device": [...]} by size."""
    plan = {"ps": [], "device": []}
    for info in infos:
        name = info["name"]
        vocab = vocab_sizes.get(name)
        if vocab is None:
            plan["ps"].append(name)  # unknown vocab: assume large
            continue
        size = vocab * info["dim"] * 4
        plan["device" if size < threshold_bytes else "ps"].append(name)
    return plan


def localize_spec(spec, vocab_sizes, tables=None, seed=0):
    """Return a new ModelSpec with the given tables (default: all below
    the PS threshold) turned into device-resident parameters."""
    infos = {i["name"]: i for i in spec.ps_embedding_infos}
    if tables is None:
        tables = plan_embedding_placement(
            spec.ps_embedding_infos, vocab_sizes
        )["device"]
    tables = [t for t in tables if t in infos]
    if not tables:
        return spec
    logger.info("localizing embedding tables onto device: %s", tables)

    base_init = spec.init_fn
    base_apply = spec.apply_fn
    base_feed = spec.feed
    local_infos = {t: infos[t] for t in tables}

    def init_fn(rng):
        params = base_init(rng)
        for i, (t, info) in enumerate(sorted(local_infos.items())):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), i)
            vocab = vocab_sizes[t]
            init = info.get("initializer", "uniform")
            if init == "zeros":
                table = jnp.zeros((vocab, info["dim"]), jnp.float32)
            else:
                table = jax.random.uniform(
                    key, (vocab, info["dim"]), jnp.float32, -0.05, 0.05
                )
            params["local_emb__" + t] = table
        return params

    def apply_fn(params, feats, train):
        feats = dict(feats)
        for t in tables:
            feats["emb__" + t] = params["local_emb__" + t]
        return base_apply(params, feats, train)

    def feed(records):
        features, labels = base_feed(records)
        ids_map = features.get("__ids__", {})
        for t in tables:
            ids = ids_map.pop(t, None)
            if ids is not None:
                features["idx__" + t] = np.asarray(ids, np.int32)
        if not ids_map:
            features.pop("__ids__", None)
        return features, labels

    return dataclasses.replace(
        spec,
        init_fn=init_fn,
        apply_fn=apply_fn,
        feed=feed,
        ps_embedding_infos=[
            i for i in spec.ps_embedding_infos
            if i["name"] not in tables
        ],
    )
