"""Declarative feature columns over the preprocessing layers.

Numpy-first counterpart of the reference's feature-column helpers
(elasticdl_preprocessing/feature_column/feature_column.py — notably
``concatenated_categorical_column``, which merges many categorical
columns into ONE offset id space so a single PS-served embedding table
backs them all: one big table beats per-column tables on both model
size and PS traffic).

Columns declare the record-dict -> model-input mapping; ``make_feed``
compiles a set of columns into the framework's feed convention
({"dense": [B, Dn], "__ids__": {table: [B, F]}}, labels) consumed by
the PS trainer's embedding machinery (worker/ps_trainer.py).

Dataset-statistics plumbing: ``*.from_stats`` constructors read the
analyzer's env-exported statistics (preprocessing/analyzer_utils.py, the
reference's ``_ELASTICDL_*`` scheme) so a feed can be configured
entirely by an offline analyzer job.
"""

import numpy as np

from elasticdl_tpu.preprocessing import analyzer_utils
from elasticdl_tpu.preprocessing.layers import (
    Discretization,
    Hashing,
    IndexLookup,
    Normalizer,
)


class FeatureColumn:
    """Base: a named transform from raw column values to arrays."""

    def __init__(self, key):
        self.key = key

    def transform(self, values):
        raise NotImplementedError


def _to_floats(values, default):
    """Float coercion with missing-value default (shared by numeric and
    bucketized columns)."""
    return np.asarray(
        [default if v in ("", None) else float(v) for v in values],
        np.float32,
    )


class NumericColumn(FeatureColumn):
    """Float feature, optionally normalized."""

    def __init__(self, key, normalizer_fn=None, default=0.0):
        super().__init__(key)
        self._normalizer = normalizer_fn
        self._default = default

    @classmethod
    def from_stats(cls, key, default=0.0):
        """Standardize with the analyzer's mean/stddev for this key."""
        mean = analyzer_utils.get_mean(key, 0.0)
        std = analyzer_utils.get_stddev(key, 1.0) or 1.0
        return cls(key, Normalizer(subtract=mean, divide=std),
                   default=default)

    def transform(self, values):
        arr = _to_floats(values, self._default)
        if self._normalizer is not None:
            arr = np.asarray(self._normalizer(arr), np.float32)
        return arr


class CategoricalColumn(FeatureColumn):
    """Base for id-producing columns; exposes ``num_buckets``."""

    num_buckets = None


class CategoricalIdentityColumn(CategoricalColumn):
    def __init__(self, key, num_buckets, default=0):
        super().__init__(key)
        self.num_buckets = num_buckets
        self._default = default

    def transform(self, values):
        ids = np.asarray(
            [self._default if v in ("", None) else int(v)
             for v in values],
            np.int64,
        )
        return np.clip(ids, 0, self.num_buckets - 1)


class CategoricalVocabColumn(CategoricalColumn):
    """Vocabulary lookup; OOV maps past the vocab (reference
    IndexLookup semantics)."""

    def __init__(self, key, vocabulary):
        super().__init__(key)
        self._lookup = IndexLookup(list(vocabulary))
        self.num_buckets = self._lookup.vocab_size()  # vocab + OOV

    @classmethod
    def from_stats(cls, key):
        vocab = analyzer_utils.get_vocabulary(key)
        if vocab is None:
            raise ValueError(
                "no analyzer vocabulary exported for %r" % key
            )
        return cls(key, vocab)

    def transform(self, values):
        # IndexLookup handles bytes/str/other renditions itself.
        return np.asarray(self._lookup(list(values)), np.int64)


class CategoricalHashColumn(CategoricalColumn):
    def __init__(self, key, hash_bucket_size):
        super().__init__(key)
        self._hashing = Hashing(hash_bucket_size)
        self.num_buckets = hash_bucket_size

    def transform(self, values):
        # Hashing dispatches by dtype (vectorized splitmix64 for ints,
        # sha256 for strings) — don't force everything through str().
        return np.asarray(self._hashing(np.asarray(values)), np.int64)


class BucketizedColumn(CategoricalColumn):
    """Numeric feature discretized into bucket ids."""

    def __init__(self, key, boundaries, default=0.0):
        super().__init__(key)
        self._disc = Discretization(list(boundaries))
        self._default = default
        self.num_buckets = len(boundaries) + 1

    @classmethod
    def from_stats(cls, key, default=0.0):
        bounds = analyzer_utils.get_bucket_boundaries(key)
        if bounds is None:
            raise ValueError(
                "no analyzer bucket boundaries exported for %r" % key
            )
        return cls(key, bounds, default=default)

    def transform(self, values):
        return np.asarray(
            self._disc(_to_floats(values, self._default)), np.int64
        )


class ConcatenatedCategoricalColumn(CategoricalColumn):
    """Merge categorical columns into one offset id space
    (reference feature_column.py concatenated_categorical_column): the
    id range becomes [0, sum of num_buckets), each source column offset
    by the buckets before it, so ONE embedding table serves all of
    them."""

    def __init__(self, columns):
        if not columns:
            raise ValueError("need at least one categorical column")
        for c in columns:
            if not isinstance(c, CategoricalColumn):
                raise ValueError(
                    "%r is not a CategoricalColumn" % (c,)
                )
            if isinstance(c, ConcatenatedCategoricalColumn):
                raise ValueError(
                    "cannot nest concatenated columns; pass the leaf "
                    "columns in one flat list"
                )
        super().__init__("+".join(c.key for c in columns))
        self.columns = list(columns)
        self.offsets = np.concatenate(
            [[0], np.cumsum([c.num_buckets for c in columns])[:-1]]
        ).astype(np.int64)
        self.num_buckets = int(sum(c.num_buckets for c in columns))

    def transform(self, record_columns):
        """record_columns: {key: [B] raw values} -> [B, F] int64 ids."""
        cols = [
            c.transform(record_columns[c.key]) + off
            for c, off in zip(self.columns, self.offsets)
        ]
        return np.stack(cols, axis=1)


def concatenated_categorical_column(columns):
    return ConcatenatedCategoricalColumn(columns)


def make_feed(numeric_columns, id_tables, label_key="label",
              label_dtype=np.int32, column_order=None):
    """Compile columns into the framework feed convention.

    numeric_columns: [NumericColumn] -> "dense" [B, Dn].
    id_tables: {table_name: ConcatenatedCategoricalColumn} -> "__ids__"
        entries, one per PS embedding table.
    Records arrive as a dict of columns ({key: [B] values}), a list of
    per-record dicts, or — when ``column_order`` names the positions —
    a list of per-record sequences (the row shape of the SQL and CSV
    readers).
    """

    def feed(records):
        if isinstance(records, list):
            first = records[0]
            if isinstance(first, dict):
                columns = {k: [r[k] for r in records] for k in first}
            else:
                if column_order is None:
                    raise ValueError(
                        "list-shaped records need column_order"
                    )
                columns = {
                    k: [r[i] for r in records]
                    for i, k in enumerate(column_order)
                }
        else:
            columns = records
        out = {}
        if numeric_columns:
            out["dense"] = np.stack(
                [c.transform(columns[c.key]) for c in numeric_columns],
                axis=1,
            )
        # Several tables may share one concat column (e.g. a wide and a
        # deep embedding over the same id space) — transform each
        # distinct column once per batch.
        cache = {}
        out["__ids__"] = {}
        for table, concat in id_tables.items():
            if id(concat) not in cache:
                cache[id(concat)] = concat.transform(columns)
            out["__ids__"][table] = cache[id(concat)]
        labels = np.asarray(columns[label_key], label_dtype)
        return out, labels

    return feed
