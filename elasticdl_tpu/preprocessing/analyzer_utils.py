"""Env-var-driven dataset statistics.

Parity with elasticdl_preprocessing/utils/analyzer_utils.py:23-50+: an
offline analyzer (or the job submitter) exports per-feature statistics into
the environment; zoo feeds read them to configure preprocessing layers.
Variable scheme: ``_EDL_TPU_<FEATURE>_<STAT>``.
"""

import json
import os

_PREFIX = "_EDL_TPU_"


def _get(feature, stat, default=None, cast=float):
    key = "%s%s_%s" % (_PREFIX, feature.upper(), stat.upper())
    value = os.environ.get(key)
    if value is None:
        return default
    return cast(value)


def get_min(feature, default=None):
    return _get(feature, "min", default)


def get_max(feature, default=None):
    return _get(feature, "max", default)


def get_mean(feature, default=None):
    return _get(feature, "avg", default)


def get_stddev(feature, default=None):
    return _get(feature, "stddev", default)


def get_distinct_count(feature, default=None):
    return _get(feature, "count_distinct", default, cast=int)


def get_bucket_boundaries(feature, default=None):
    value = _get(feature, "bucket_boundaries", None, cast=str)
    if value is None:
        return default
    return json.loads(value)


def get_vocabulary(feature, default=None):
    value = _get(feature, "vocabulary", None, cast=str)
    if value is None:
        return default
    return json.loads(value)


def set_stats(feature, **stats):
    """Export stats into the env (what the analyzer job does)."""
    for stat, value in stats.items():
        key = "%s%s_%s" % (_PREFIX, feature.upper(), stat.upper())
        if isinstance(value, (list, dict)):
            value = json.dumps(value)
        os.environ[key] = str(value)
