"""Feature preprocessing — the elasticdl_preprocessing equivalent.

The reference ships 11 Keras layers (SURVEY.md §2.10,
elasticdl_preprocessing/layers/*). Here they are small callables with the
same names and semantics, built on numpy/jnp:

 - host path (inside a zoo ``feed``): numpy in, numpy out — fast record
   munging before the batch crosses to the device;
 - device path: the numeric transforms (Discretization, Normalizer,
   RoundIdentity, LogRound, Hashing over ints, SparseEmbedding combiners)
   are jnp-compatible and jit-safe.

Ragged/sparse TF structures map to a single TPU-friendly representation:
``RaggedBatch`` (flat values + row lengths) with ``to_dense`` producing the
static-shape padded array + mask that XLA wants.
"""

import hashlib

import numpy as np

try:
    import jax.numpy as jnp
except ImportError:  # pragma: no cover
    jnp = None


def _xp(x):
    """numpy for host arrays, jnp for traced/jax arrays."""
    if jnp is not None and not isinstance(x, (np.ndarray, list, tuple,
                                              int, float)):
        return jnp
    return np


class RaggedBatch:
    """Variable-length rows: flat values + per-row lengths.

    The TPU-native stand-in for tf.RaggedTensor/SparseTensor (ToRagged /
    ToSparse below build it); ``to_dense`` yields [batch, max_len] +
    float mask for static-shape device code.
    """

    def __init__(self, values, row_lengths):
        self.values = np.asarray(values)
        self.row_lengths = np.asarray(row_lengths, dtype=np.int64)

    @classmethod
    def from_rows(cls, rows):
        rows = [np.asarray(r) for r in rows]
        lengths = [r.size for r in rows]
        values = (
            np.concatenate([r.reshape(-1) for r in rows])
            if rows else np.zeros((0,))
        )
        return cls(values, lengths)

    def rows(self):
        out = []
        start = 0
        for n in self.row_lengths:
            out.append(self.values[start:start + n])
            start += n
        return out

    def to_dense(self, max_len=None, padding_value=0):
        max_len = max_len or (
            int(self.row_lengths.max()) if len(self.row_lengths) else 0
        )
        dense = np.full(
            (len(self.row_lengths), max_len), padding_value,
            dtype=self.values.dtype,
        )
        mask = np.zeros((len(self.row_lengths), max_len), np.float32)
        start = 0
        for i, n in enumerate(self.row_lengths):
            k = min(int(n), max_len)
            dense[i, :k] = self.values[start:start + k]
            mask[i, :k] = 1.0
            start += n
        return dense, mask

    def map_values(self, fn):
        return RaggedBatch(fn(self.values), self.row_lengths)


def _apply(inputs, fn):
    if isinstance(inputs, RaggedBatch):
        return inputs.map_values(fn)
    return fn(inputs)


class Discretization:
    """Bucketize by boundaries; output in [0, len(bins)]
    (reference: layers/discretization.py:20)."""

    def __init__(self, bin_boundaries):
        self.bin_boundaries = np.asarray(bin_boundaries, np.float64)

    def __call__(self, inputs):
        return _apply(
            inputs,
            lambda x: np.digitize(np.asarray(x, np.float64),
                                  self.bin_boundaries).astype(np.int64),
        )


class Hashing:
    """Deterministic hash to [0, num_bins)
    (reference: layers/hashing.py:19).  Integers use a splitmix64 mix
    (jit-safe); strings/bytes hash via sha256 on the host."""

    def __init__(self, num_bins, salt=0):
        self.num_bins = num_bins
        self.salt = salt

    def _hash_int_array(self, x):
        xp = _xp(x)
        z = xp.asarray(x).astype(xp.uint64) + xp.uint64(
            0x9E3779B97F4A7C15 + self.salt
        )
        z = (z ^ (z >> xp.uint64(30))) * xp.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> xp.uint64(27))) * xp.uint64(0x94D049BB133111EB)
        z = z ^ (z >> xp.uint64(31))
        return (z % xp.uint64(self.num_bins)).astype(xp.int64)

    def _hash_one(self, value):
        data = str(value).encode("utf-8") + str(self.salt).encode()
        return int(hashlib.sha256(data).hexdigest(), 16) % self.num_bins

    def __call__(self, inputs):
        def fn(x):
            arr = np.asarray(x) if isinstance(
                x, (np.ndarray, list, tuple)
            ) else x
            if hasattr(arr, "dtype") and np.issubdtype(
                np.asarray(arr).dtype if isinstance(arr, np.ndarray)
                else arr.dtype, np.integer
            ):
                return self._hash_int_array(arr)
            flat = np.asarray(arr).reshape(-1)
            out = np.array([self._hash_one(v) for v in flat], np.int64)
            return out.reshape(np.shape(arr))
        return _apply(inputs, fn)


class IndexLookup:
    """Vocabulary lookup; OOV maps to len(vocab)
    (reference: layers/index_lookup.py:22)."""

    def __init__(self, vocabulary):
        self.vocabulary = list(vocabulary)
        self._table = {v: i for i, v in enumerate(self.vocabulary)}
        self.oov_index = len(self.vocabulary)

    def __call__(self, inputs):
        def fn(x):
            flat = np.asarray(x, dtype=object).reshape(-1)
            out = np.array(
                [self._table.get(
                    v.decode() if isinstance(v, bytes) else str(v),
                    self.oov_index,
                ) for v in flat],
                np.int64,
            )
            return out.reshape(np.shape(x))
        return _apply(inputs, fn)

    def vocab_size(self):
        return len(self.vocabulary) + 1  # + OOV


class LogRound:
    """round(log_base(x)) clipped to [0, num_bins)
    (reference: layers/log_round.py:29)."""

    def __init__(self, num_bins, base=None, default_value=0):
        self.num_bins = num_bins
        self.base = base or np.e
        self.default_value = default_value

    def __call__(self, inputs):
        def fn(x):
            xp = _xp(x)
            x = xp.asarray(x, xp.float64) if xp is np else x.astype(
                "float32"
            )
            safe = xp.where(x > 0, x, 1.0)
            out = xp.round(xp.log(safe) / np.log(self.base))
            out = xp.where(x > 0, out, self.default_value)
            return xp.clip(out, 0, self.num_bins - 1).astype(xp.int64)
        return _apply(inputs, fn)


class Normalizer:
    """(x - subtract) / divide (reference: layers/normalizer.py:17)."""

    def __init__(self, subtract=0.0, divide=1.0):
        self.subtract = subtract
        self.divide = divide

    def __call__(self, inputs):
        return _apply(
            inputs,
            lambda x: (_xp(x).asarray(x) - self.subtract) / self.divide,
        )


class RoundIdentity:
    """round(x) clipped to [0, num_buckets)
    (reference: layers/round_identity.py:18)."""

    def __init__(self, num_buckets, default_value=0):
        self.num_buckets = num_buckets
        self.default_value = default_value

    def __call__(self, inputs):
        def fn(x):
            xp = _xp(x)
            out = xp.round(xp.asarray(x))
            return xp.clip(out, 0, self.num_buckets - 1).astype(xp.int64)
        return _apply(inputs, fn)


class ToNumber:
    """Parse strings to numbers; empty/invalid -> default
    (reference: layers/to_number.py:33)."""

    def __init__(self, out_type=np.float32, default_value=0):
        self.out_type = out_type
        self.default_value = default_value

    def __call__(self, inputs):
        def one(v):
            if isinstance(v, bytes):
                v = v.decode()
            try:
                return self.out_type(v)
            except (TypeError, ValueError):
                return self.out_type(self.default_value)

        def fn(x):
            flat = np.asarray(x, dtype=object).reshape(-1)
            out = np.array([one(v) for v in flat], dtype=self.out_type)
            return out.reshape(np.shape(x))
        return _apply(inputs, fn)


class ToRagged:
    """Split delimiter-joined strings (or take per-row lists) into a
    RaggedBatch (reference: layers/to_ragged.py:19)."""

    def __init__(self, sep=",", ignore_value=""):
        self.sep = sep
        self.ignore_value = ignore_value

    def __call__(self, inputs):
        rows = []
        for item in inputs:
            if isinstance(item, bytes):
                item = item.decode()
            if isinstance(item, str):
                parts = [
                    p for p in item.split(self.sep)
                    if p != self.ignore_value
                ]
                rows.append(np.asarray(parts, dtype=object))
            else:
                rows.append(np.asarray(item))
        return RaggedBatch.from_rows(rows)


class ToSparse:
    """Alias view: same RaggedBatch representation; kept for API parity
    (reference: layers/to_sparse.py:17)."""

    def __init__(self, ignore_value=""):
        self.ignore_value = ignore_value

    def __call__(self, inputs):
        if isinstance(inputs, RaggedBatch):
            return inputs
        return ToRagged(ignore_value=self.ignore_value)(inputs)


class ConcatenateWithOffset:
    """Add per-tensor offsets then concatenate
    (reference: layers/concatenate_with_offset.py:17)."""

    def __init__(self, offsets, axis=-1):
        self.offsets = offsets
        self.axis = axis

    def __call__(self, inputs):
        if len(inputs) != len(self.offsets):
            raise ValueError(
                "%d inputs vs %d offsets"
                % (len(inputs), len(self.offsets))
            )
        if isinstance(inputs[0], RaggedBatch):
            shifted = [
                rb.map_values(lambda v, o=o: np.asarray(v) + o)
                for rb, o in zip(inputs, self.offsets)
            ]
            rows_per_input = [rb.rows() for rb in shifted]
            merged = [
                np.concatenate([rows[i] for rows in rows_per_input])
                for i in range(len(rows_per_input[0]))
            ]
            return RaggedBatch.from_rows(merged)
        xp = _xp(inputs[0])
        shifted = [
            xp.asarray(x) + o for x, o in zip(inputs, self.offsets)
        ]
        return xp.concatenate(shifted, axis=self.axis)


class SparseEmbedding:
    """Combiner over embedding rows of padded ids with a mask — the
    device half of the reference's SparseEmbedding layer
    (layers/sparse_embedding.py:20).  jit-safe and differentiable.

    rows: [B, L, dim] gathered embeddings; mask: [B, L].
    combiner: sum | mean | sqrtn
    """

    def __init__(self, combiner="mean"):
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError("unknown combiner %r" % combiner)
        self.combiner = combiner

    def __call__(self, rows, mask):
        xp = jnp if jnp is not None else np
        mask = xp.asarray(mask)[..., None]
        total = (xp.asarray(rows) * mask).sum(axis=1)
        count = xp.maximum(mask.sum(axis=1), 1e-9)
        if self.combiner == "sum":
            return total
        if self.combiner == "mean":
            return total / count
        return total / xp.sqrt(count)
