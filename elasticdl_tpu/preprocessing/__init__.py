from elasticdl_tpu.preprocessing import analyzer_utils, feature_column  # noqa: F401
