"""PServer gRPC servicer — async and sync SGD semantics.

Parity with elasticdl/python/ps/servicer.py:33-290 and
go/pkg/ps/server.go:54-253:

 - async: every gradient push applies immediately, version++ per push,
   optional staleness-modulated learning rate (1/staleness)
 - sync: buffer pushes until ``grads_to_wait``; average dense, concatenate
   sparse; reject pushes whose model version lags beyond
   ``sync_version_tolerance`` (worker re-pulls and retries the minibatch)
 - checkpoint every ``checkpoint_steps`` versions; report version to the
   master every ``evaluation_steps`` versions

Restart-generation fencing (docs/ps_recovery.md): every response on the
data plane carries this incarnation's ``generation`` (monotone across
restarts, established by ps/server.py).  A push or prepare stamped with
a DIFFERENT generation was computed against a dead incarnation's state
and is rejected outright — in async mode the version check alone would
happily mis-apply it to the restored (older-version) state as a
"future-version" gradient.  A pull whose request carries a stale
generation bypasses the ``request.version < version`` fast path, because
after a crash-restore rollback that check points the wrong way.
``generation`` is fixed for the life of the process, so fencing reads it
without the update lock.
"""

import threading
import time

import numpy as np

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import tensor_codec, tracing
from elasticdl_tpu.utils.grpc_utils import rpc_error_guard
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.timing import Timing

logger = get_logger(__name__)


class PserverServicer:
    def __init__(
        self,
        parameters,
        optimizer,
        ps_id=0,
        num_ps=1,
        use_async=True,
        grads_to_wait=1,
        sync_version_tolerance=0,
        lr_staleness_modulation=False,
        checkpoint_saver=None,
        checkpoint_steps=0,
        evaluation_steps=0,
        master_client=None,
        generation=1,
    ):
        self._params = parameters
        self._opt = optimizer
        self._ps_id = ps_id
        self._num_ps = num_ps
        self._use_async = use_async
        self._grads_to_wait = grads_to_wait
        self._sync_version_tolerance = sync_version_tolerance
        self._lr_staleness_modulation = lr_staleness_modulation
        self._checkpoint_saver = checkpoint_saver
        self._checkpoint_steps = checkpoint_steps
        self._evaluation_steps = evaluation_steps
        self._master_client = master_client
        # Restart incarnation; IMMUTABLE for the life of the process
        # (bumped by ps/server.py on every start), so fencing checks
        # read it lock-free.
        self.generation = max(1, int(generation))
        # Last version verifiably on disk (this incarnation); guarded
        # by self._lock like the checkpoint path that writes it.
        self._durable_version = 0
        self._lock = threading.Lock()
        self._grad_buffer = []   # [(dense, embeddings)] awaiting sync apply
        self._staged = {}        # txn_id -> (dense, emb, lr, stage_time)
        self._staged_ttl = 60.0  # abandon prepares from dead workers
        # Observability counters (ps/server.py --status_port).  Bumps
        # happen under self._lock EXCEPT pull_embedding, which is
        # deliberately lock-free — that one counter tolerates rare
        # lost increments rather than re-serializing the hot RPC.
        self.counters = {"push_accepted": 0, "push_rejected": 0,
                         "push_gen_rejected": 0, "ps_ckpt_failed": 0,
                         "pull_dense": 0, "pull_embedding": 0,
                         "pull_embedding_ro": 0}
        # Data-plane byte accounting per wire encoding (the frame-vs-pb
        # bench artifact, surfaced as elasticdl_ps_wire_bytes{kind=} on
        # /metrics): payload bytes received/sent, plus the bytes the
        # decode path had to COPY to produce consumable ndarrays —
        # zero-copy frames alias the gRPC message, TensorPB pays a full
        # content materialization per tensor (tensor_codec decode-copy
        # accounting).  Bumped under self._lock like the counters.
        self.wire_counters = {
            "push_payload_pb": 0, "push_payload_frame": 0,
            "push_decode_copy_pb": 0, "push_decode_copy_frame": 0,
            "pull_dense_payload_pb": 0, "pull_dense_payload_frame": 0,
        }
        # Handle-time histograms for the data-plane RPCs (push/pull),
        # rendered as native Prometheus histograms on the shard's
        # /metrics (utils/prom.ps_to_prometheus).  Durations use local
        # starts + observe — these RPCs fan out on the 64-thread gRPC
        # server, so the shared timeit starts dict would corrupt.
        self.timing = Timing()

    # -- RPCs ---------------------------------------------------------------

    @rpc_error_guard
    def push_model(self, request, _context=None):
        # Under the servicer lock: slot-table creation must not overlap
        # a concurrent push's apply (init_from_model_pb is internally
        # idempotent, but the slot tables are not).
        with self._lock:
            self._params.init_from_model_pb(request)
            self._params.create_slot_tables(self._opt.slot_names)
        return pb.Empty()

    @rpc_error_guard
    def push_embedding_table_infos(self, request, _context=None):
        _, _, infos, _ = tensor_codec.pb_to_model(request)
        with self._lock:
            self._params.set_embedding_infos(infos)
            self._params.create_slot_tables(self._opt.slot_names)
        return pb.Empty()

    @rpc_error_guard
    def pull_dense_parameters(self, request, _context=None):
        t0 = time.perf_counter()
        try:
            return self._pull_dense_parameters(request)
        finally:
            self.timing.observe("ps.pull_dense",
                                time.perf_counter() - t0)

    def _pull_dense_parameters(self, request):
        res = pb.PullDenseParametersResponse()
        res.generation = self.generation
        # Advertise the raw-frame data plane: a capable client upgrades
        # this shard's push/pull traffic to the *_frame methods after
        # its first legacy pull (docs/ps_pipeline.md "Frame wire").
        res.frame_capable = True
        # A client that last observed a different incarnation gets the
        # full dense state regardless of its version: after a crash-
        # restore rollback the server's version is BELOW the client's,
        # so the fast-path comparison alone would starve it of the
        # restored state forever (0 = client has no generation yet; the
        # version check governs, as before fencing existed).
        stale_gen = bool(request.generation) and (
            request.generation != self.generation
        )
        # Serialize against in-place kernel updates so pulls never see a
        # half-applied parameter buffer.
        with self._lock:
            self.counters["pull_dense"] += 1
            res.initialized = self._params.initialized
            res.version = self._params.version
            if self._params.initialized and (
                request.version < self._params.version
                or request.version < 0
                or stale_gen
            ):
                for name, arr in self._params.get_dense().items():
                    tensor_codec.ndarray_to_pb(
                        arr, out=res.dense_parameters[name]
                    )
            self.wire_counters["pull_dense_payload_pb"] += (
                res.ByteSize()
            )
        return res

    @rpc_error_guard
    def pull_dense_parameters_frame(self, request, _context=None):
        """Frame-native dense pull (docs/ps_pipeline.md "Frame wire"):
        same request/fast-path/fencing semantics as the pb method, but
        the response is ONE params frame blob (RawFrame identity codec)
        instead of repeated per-tensor TensorPB copies.  The
        not-modified fast path is a tensorless frame whose header meta
        still carries initialized/version/generation."""
        t0 = time.perf_counter()
        try:
            return self._pull_dense_parameters_frame(request)
        finally:
            self.timing.observe("ps.pull_dense",
                                time.perf_counter() - t0)

    def _pull_dense_parameters_frame(self, request):
        stale_gen = bool(request.generation) and (
            request.generation != self.generation
        )
        with self._lock:
            self.counters["pull_dense"] += 1
            initialized = self._params.initialized
            version = self._params.version
            dense = None
            if initialized and (
                request.version < version
                or request.version < 0
                or stale_gen
            ):
                dense = self._params.get_dense()
            # Encode UNDER the lock: encode_frame reads the parameter
            # buffers (tobytes), and a concurrent in-place apply would
            # tear them — the same reason the pb path encodes under it.
            blob = tensor_codec.encode_params_frame(
                dense, version=version, initialized=initialized,
                generation=self.generation,
            )
            self.wire_counters["pull_dense_payload_frame"] += len(blob)
        return blob

    @rpc_error_guard
    def pull_embedding_vectors(self, request, _context=None):
        t0 = time.perf_counter()
        try:
            return self._pull_embedding_vectors(request)
        finally:
            self.timing.observe("ps.pull_embedding",
                                time.perf_counter() - t0)

    def _pull_embedding_vectors(self, request):
        # No servicer lock: the native table's rw-lock (kernels.cc)
        # makes each ROW read/write atomic, so embedding traffic from
        # many workers no longer serializes behind dense updates — this
        # is the RPC the 64-thread gRPC server actually fans out.
        # Guarantee is per-row, not a cross-row snapshot: a concurrent
        # push can land between rows of one pull (uninitialized ids take
        # a second lock acquisition), which async SGD tolerates by
        # design — the same per-row semantics as the reference's Go
        # table (embedding_table.go:41-58 under RWMutex).
        if request.read_only:
            # Serving-tier lookup (docs/serving.md fleet section): a
            # read-mostly client must never grow the training table, so
            # absent ids come back as zero rows instead of being lazily
            # initialized — matching the exported-table lookup's
            # ``default=0.0`` semantics bit for bit.
            self.counters["pull_embedding_ro"] += 1
            vectors = self._params.lookup_embedding_rows(
                request.name, np.asarray(request.ids, np.int64)
            )
        else:
            self.counters["pull_embedding"] += 1
            vectors = self._params.pull_embedding_vectors(
                request.name, np.asarray(request.ids, np.int64)
            )
        # The master copy stays float32; the client may ask for a
        # reduced-precision wire encoding (request.wire_dtype, e.g.
        # "bfloat16") to halve the pull bandwidth — the codec upcasts
        # transparently on decode.
        res = tensor_codec.ndarray_to_pb(
            vectors, wire_dtype=request.wire_dtype or None
        )
        # Generation stamp on the lookup response: an embedding-only
        # client (the serving hot-row cache) otherwise never learns
        # about a crash-restore rollback — this is the PR-8 fencing
        # plane extended to the read-mostly path, so version-keyed
        # caches can invalidate rows read from a dead incarnation.
        res.generation = self.generation
        return res

    def _fence(self, request_generation):
        """Restart fencing: a push/prepare stamped by another incarnation
        is rejected before any decode or apply.  ``self.generation`` is
        immutable, so the check is lock-free; the lock is taken only to
        bump the counter and read a coherent version for the response.
        Returns the reject response, or None to proceed (0 = unstamped
        legacy client: accept, the version checks govern)."""
        if not request_generation or request_generation == self.generation:
            return None
        with self._lock:
            self.counters["push_gen_rejected"] += 1
            version = self._params.version
        # In the PUSHER's trace (server span): the fence as the shard
        # saw it — a churn drill's timeline shows which worker's dead-
        # incarnation push was refused, and when.
        tracing.event("ps.push_fenced",
                      dead_generation=request_generation,
                      generation=self.generation, version=version)
        logger.warning(
            "rejecting gradients stamped by generation %d (serving "
            "generation %d): pushed by a dead incarnation's worker view",
            request_generation, self.generation,
        )
        return pb.PushGradientsResponse(
            accepted=False, version=version, generation=self.generation
        )

    @rpc_error_guard
    def push_gradients(self, request, _context=None):
        t0 = time.perf_counter()
        try:
            return self._push_gradients(request)
        finally:
            self.timing.observe("ps.push_handle",
                                time.perf_counter() - t0)

    def _push_gradients(self, request):
        fenced = self._fence(request.generation)
        if fenced is not None:
            return fenced
        dense, embeddings, _, grad_version = tensor_codec.pb_to_model(
            request.gradients
        )
        return self._handle_push(
            dense, embeddings, grad_version,
            request.learning_rate or None,
            wire=("pb", request.gradients.ByteSize(),
                  tensor_codec.model_pb_decode_copy_bytes(
                      request.gradients)),
        )

    @rpc_error_guard
    def push_gradients_frame(self, request, _context=None):
        """Frame-native gradient push (docs/ps_pipeline.md "Frame
        wire"): ``request`` IS the frame blob (RawFrame identity
        codec).  Fencing reads ``generation`` from the PEEKED header
        meta, so a push stamped by a dead incarnation is rejected
        before any payload decode; the decode itself hands back
        zero-copy views over the gRPC message bytes, fed straight into
        the same apply path as the pb method.  A malformed blob raises
        FrameError, which rpc_error_guard surfaces as a loud
        INVALID_ARGUMENT with the server intact."""
        t0 = time.perf_counter()
        try:
            header = tensor_codec.peek_frame_header(request)
            generation = tensor_codec.frame_meta(header).get(
                "generation") or 0
            if not isinstance(generation, int):
                raise tensor_codec.FrameError(
                    "meta generation %r is not an integer"
                    % (generation,))
            fenced = self._fence(generation)
            if fenced is not None:
                return fenced
            dense, embeddings, grad_version, lr = (
                tensor_codec.decode_grads_frame(request)
            )
            return self._handle_push(
                dense, embeddings, grad_version, lr or None,
                wire=("frame", len(request),
                      tensor_codec.frame_decode_copy_bytes(header)),
            )
        finally:
            self.timing.observe("ps.push_handle",
                                time.perf_counter() - t0)

    def _handle_push(self, dense, embeddings, grad_version, lr_override,
                     wire=None):
        """Decoded-gradient apply shared by the pb and frame push
        paths — one body, so the two wire encodings stay bit-identical
        in everything that matters (staleness checks, lr modulation,
        sync buffering, version/report bookkeeping).  ``wire`` is the
        (encoding, payload_bytes, decode_copy_bytes) accounting triple,
        folded into ``wire_counters`` under the lock."""
        report = None
        with self._lock:
            if wire is not None:
                encoding, payload_bytes, copy_bytes = wire
                self.wire_counters["push_payload_" + encoding] += (
                    payload_bytes
                )
                self.wire_counters["push_decode_copy_" + encoding] += (
                    copy_bytes
                )
            if self._use_async:
                lr_mult = 1.0
                if self._lr_staleness_modulation:
                    staleness = max(
                        1, self._params.version - grad_version
                    )
                    lr_mult = 1.0 / staleness
                self._apply_locked(dense, embeddings, lr_mult, lr_override)
                self._params.version += 1
                version = self._params.version
                report = self._post_update_locked()
                self.counters["push_accepted"] += 1
                res = pb.PushGradientsResponse(
                    accepted=True, version=version
                )
            elif grad_version < (
                self._params.version - self._sync_version_tolerance
            ):
                # sync mode, stale
                self.counters["push_rejected"] += 1
                res = pb.PushGradientsResponse(
                    accepted=False, version=self._params.version
                )
            else:
                self._grad_buffer.append((dense, embeddings))
                if len(self._grad_buffer) < self._grads_to_wait:
                    self.counters["push_accepted"] += 1
                    res = pb.PushGradientsResponse(
                        accepted=True, version=self._params.version
                    )
                else:
                    dense_sum, emb_cat = self._reduce_buffer_locked()
                    self._grad_buffer.clear()
                    self._apply_locked(dense_sum, emb_cat, 1.0, lr_override)
                    self._params.version += 1
                    version = self._params.version
                    report = self._post_update_locked()
                    self.counters["push_accepted"] += 1
                    res = pb.PushGradientsResponse(
                        accepted=True, version=version
                    )
        res.generation = self.generation
        self._report_version(report)
        return res

    @rpc_error_guard
    def prepare_gradients(self, request, _context=None):
        """Phase 1 of the cross-shard atomic sync push: run the staleness
        check and stage the gradients.  Nothing is applied until commit,
        so a reject on any sibling shard can abort everywhere — no shard
        ever half-applies a minibatch (reference semantics were per-shard,
        python/ps/servicer.py:168-238; this closes that gap).  A prepare
        stamped by a dead incarnation is fenced like a push, so the 2PC
        aborts cleanly on EVERY shard when one shard died mid-protocol."""
        fenced = self._fence(request.generation)
        if fenced is not None:
            return fenced
        dense, embeddings, _, grad_version = tensor_codec.pb_to_model(
            request.gradients
        )
        with self._lock:
            now = time.monotonic()
            for txn in [
                t for t, (_, _, _, ts) in self._staged.items()
                if now - ts > self._staged_ttl
            ]:
                del self._staged[txn]  # worker died between phases
            if not self._use_async and grad_version < (
                self._params.version - self._sync_version_tolerance
            ):
                self.counters["push_rejected"] += 1
                return pb.PushGradientsResponse(
                    accepted=False, version=self._params.version,
                    generation=self.generation,
                )
            self._staged[request.txn_id] = (
                dense, embeddings, request.learning_rate or None, now
            )
            return pb.PushGradientsResponse(
                accepted=True, version=self._params.version,
                generation=self.generation,
            )

    @rpc_error_guard
    def commit_gradients(self, request, _context=None):
        """Phase 2: fold the staged entry into the sync buffer (or apply
        immediately in async mode), or drop it on abort.  Commit is
        unconditional — staleness was settled at prepare, so the
        effective tolerance is ``sync_version_tolerance`` plus in-flight
        commit concurrency (bounded by the worker count)."""
        report = None
        with self._lock:
            staged = self._staged.pop(request.txn_id, None)
            if not request.commit or staged is None:
                res = pb.PushGradientsResponse(
                    accepted=False, version=self._params.version
                )
            else:
                # Counted at COMMIT, the point a 2PC push becomes real —
                # prepare-stage rejects count as push_rejected above.
                self.counters["push_accepted"] += 1
                dense, embeddings, lr_override, _ = staged
                if self._use_async:
                    self._apply_locked(dense, embeddings, 1.0, lr_override)
                    self._params.version += 1
                    report = self._post_update_locked()
                else:
                    self._grad_buffer.append((dense, embeddings))
                    if len(self._grad_buffer) >= self._grads_to_wait:
                        dense_sum, emb_cat = self._reduce_buffer_locked()
                        self._grad_buffer.clear()
                        self._apply_locked(
                            dense_sum, emb_cat, 1.0, lr_override
                        )
                        self._params.version += 1
                        report = self._post_update_locked()
                res = pb.PushGradientsResponse(
                    accepted=True, version=self._params.version
                )
        res.generation = self.generation
        self._report_version(report)
        return res

    # -- internals ----------------------------------------------------------

    @property
    def durable_version(self):
        """Last version verifiably on disk for this shard (0 = none) —
        the shard's contribution to the cross-shard commit mark."""
        with self._lock:
            return self._durable_version

    def seed_durable_version(self, version):
        """Restore-time seeding (ps/server.py _restore): the label this
        incarnation restored from IS on disk, so the first version
        report must not drag the master's commit mark to 0."""
        with self._lock:
            self._durable_version = max(self._durable_version, version)

    def _reduce_buffer_locked(self):
        """Average dense grads; concatenate sparse grads (summing happens
        per-id inside the kernels after a merge)."""
        n = len(self._grad_buffer)
        dense_sum = {}
        emb_cat = {}
        for dense, embeddings in self._grad_buffer:
            for name, g in dense.items():
                if name in dense_sum:
                    dense_sum[name] = dense_sum[name] + g
                else:
                    dense_sum[name] = np.array(g, np.float32)
            for name, (values, ids) in embeddings.items():
                if name in emb_cat:
                    pv, pi = emb_cat[name]
                    emb_cat[name] = (
                        np.concatenate([pv, values]),
                        np.concatenate([pi, ids]),
                    )
                else:
                    emb_cat[name] = (np.asarray(values), np.asarray(ids))
        for name in dense_sum:
            dense_sum[name] = dense_sum[name] / n
        merged = {
            name: tensor_codec.merge_indexed_slices(values, ids)
            for name, (values, ids) in emb_cat.items()
        }
        return dense_sum, merged

    def _apply_locked(self, dense, embeddings, lr_mult, lr_override):
        emb = {}
        for name, (values, ids) in embeddings.items():
            values, ids = tensor_codec.merge_indexed_slices(values, ids)
            emb[name] = (values, ids)
        if lr_override:
            lr_mult = lr_mult * (lr_override / self._opt.learning_rate)
        self._opt.apply_gradients(
            self._params, dense, emb, lr_multiplier=lr_mult
        )

    def checkpoint_now(self):
        """Write this shard's checkpoint at the CURRENT version, under
        the update lock — the SIGTERM path (ps/server.py
        stop(checkpoint=True)) can land while a push_gradients apply is
        mid-flight, and a torn params/slots snapshot would restore a
        state that never existed.  Returns True iff the save landed."""
        with self._lock:
            return self._checkpoint_locked()

    def _checkpoint_locked(self):
        """Body of checkpoint_now; caller holds self._lock (the
        periodic path _post_update_locked already runs under it — the lock is
        not reentrant).  A failed save is surfaced, not just logged:
        the ``ps_ckpt_failed`` counter bumps and ``_durable_version``
        stays behind, so the version reports to the master keep
        carrying the TRUE durable mark — operators (and the recovery
        drill) can see that a restore would lose more than one
        checkpoint cadence."""
        if self._checkpoint_saver is None:
            return False
        v = self._params.version
        try:
            dense, embeddings = self._params.to_checkpoint_payload()
            # Dense optimizer slot state rides along under an
            # "optslot/" prefix so a restored shard resumes
            # Adam/Momentum trajectories (the embedding slot tables
            # are already in the payload).
            for key, arr in self._opt.slots_to_payload().items():
                dense["optslot/" + key] = arr
            self._checkpoint_saver.save_shard(
                v, self._ps_id, self._num_ps,
                dense=dense, embeddings=embeddings,
            )
        except OSError as e:
            # Sibling shards GC concurrently; a lost checkpoint must
            # never fail the worker's push RPC.
            self.counters["ps_ckpt_failed"] += 1
            tracing.event("ps.checkpoint_failed", version=v,
                          error=str(e)[:200])
            logger.warning("checkpoint at v%d failed: %s", v, e)
            return False
        self._durable_version = v
        tracing.event("ps.checkpoint", version=v)
        return True

    def _post_update_locked(self):
        """Checkpoint if due; returns the (version, durable_version)
        pair to report to the master, or None.  The report itself is an
        RPC and must happen OUTSIDE self._lock — holding the update
        lock across the master's round trip would convoy every
        concurrent pull/push behind it (EL006) — so callers release
        first, then pass the returned pair to ``_report_version``.  A
        checkpoint-cadence version always reports (not only the
        evaluation cadence): that report is how the master's
        report_version plane learns the durable commit mark
        (docs/ps_recovery.md, coordinated checkpoints)."""
        v = self._params.version
        ckpt_due = (
            self._checkpoint_saver is not None
            and self._checkpoint_steps
            and v % self._checkpoint_steps == 0
        )
        if ckpt_due:
            self._checkpoint_locked()
        report_due = (
            self._evaluation_steps and v % self._evaluation_steps == 0
        )
        if self._master_client is not None and (ckpt_due or report_due):
            return v, self._durable_version
        return None

    def _report_version(self, report):
        """Master-RPC half of _post_update_locked; call UNLOCKED.

        Outage riding lives in the client's SHORT retry policy
        (ps/server.py builds the MasterClient with a few-second
        budget — this runs inline on the push path); a master gone
        past that budget is logged and skipped, never fatal."""
        if report is None:
            return
        v, durable = report
        try:
            self._master_client.report_version(
                v, ps_id=self._ps_id, generation=self.generation,
                durable_version=durable,
            )
        except Exception as e:  # noqa: BLE001 — master may be gone
            logger.warning("report_version failed: %s", e)
