"""PS-side optimizers backed by the native kernels.

Parity with the Go optimizer layer (go/pkg/ps/optimizer.go:27-390): each
optimizer exposes dense and sparse (embedding-table) application, keeps its
slot state (velocity / m / v / accumulator) as shadow buffers, and is
constructed from ``opt_type`` + "k=v;k=v" ``opt_args`` strings.
"""

import numpy as np

from elasticdl_tpu.native import bindings as nk
from elasticdl_tpu.ps.parameters import slot_table_name
from elasticdl_tpu.utils.args import parse_opt_args


class Optimizer:
    slot_names = ()

    def __init__(self, learning_rate=0.1):
        self.learning_rate = float(learning_rate)
        self._dense_slots = {}   # (param_name, slot) -> np array
        self.step = 0

    def _slot(self, name, slot, shape):
        key = (name, slot)
        if key not in self._dense_slots:
            self._dense_slots[key] = np.zeros(shape, np.float32)
        return self._dense_slots[key]

    def apply_dense(self, name, param, grad, lr):
        raise NotImplementedError

    def apply_sparse(self, params, table_name, ids, grads, lr):
        raise NotImplementedError

    def apply_gradients(self, params, dense_grads, embedding_grads,
                        lr_multiplier=1.0):
        """dense_grads: {name: array}; embedding_grads:
        {table: (values, ids)} with ids already deduplicated."""
        self.step += 1
        lr = self.learning_rate * lr_multiplier
        for name, grad in dense_grads.items():
            param = params.dense.get(name)
            if param is None:
                raise KeyError("unknown dense parameter %r" % name)
            if param.shape != np.shape(grad):
                raise ValueError(
                    "gradient shape %s != param shape %s for %r"
                    % (np.shape(grad), param.shape, name)
                )
            self.apply_dense(
                name, param, np.ascontiguousarray(grad, np.float32), lr
            )
        for table_name, (values, ids) in embedding_grads.items():
            # The native kernels read raw float32 rows; a reduced-
            # precision wire decode or a non-contiguous merge result
            # must never reach them as-is.
            self.apply_sparse(
                params, table_name, ids,
                np.ascontiguousarray(values, np.float32), lr,
            )

    def _slot_table(self, params, table_name, slot):
        return params.slot_tables[slot_table_name(table_name, slot)]

    # -- checkpoint ---------------------------------------------------------

    def slots_to_payload(self):
        """Dense slot state + step counter for checkpoints.

        The reference Go PS persists slot state as shadow models inside the
        checkpoint (go/pkg/ps/optimizer.go:43-73 slot models +
        checkpoint.go:136-141); without this an Adam restore silently
        resets m/v to zero and bias correction to step 1.
        """
        payload = {"__step__": np.array([self.step], np.int64)}
        for (name, slot), arr in self._dense_slots.items():
            payload["%s@%s" % (name, slot)] = arr.copy()
        return payload

    def restore_slots_from_payload(self, payload):
        for key, arr in payload.items():
            if key == "__step__":
                self.step = int(np.asarray(arr).reshape(-1)[0])
            else:
                name, slot = key.rsplit("@", 1)
                self._dense_slots[(name, slot)] = np.array(
                    arr, np.float32, copy=True
                )


class SGD(Optimizer):
    def apply_dense(self, name, param, grad, lr):
        nk.sgd(param, grad, lr)

    def apply_sparse(self, params, table_name, ids, grads, lr):
        params.embeddings[table_name].apply_sgd(ids, grads, lr)


class Momentum(Optimizer):
    slot_names = ("momentum",)

    def __init__(self, learning_rate=0.1, momentum=0.9, nesterov=False):
        super().__init__(learning_rate)
        self.momentum = float(momentum)
        self.nesterov = bool(nesterov)

    def apply_dense(self, name, param, grad, lr):
        vel = self._slot(name, "momentum", param.shape)
        nk.momentum(param, grad, vel, lr, self.momentum, self.nesterov)

    def apply_sparse(self, params, table_name, ids, grads, lr):
        params.embeddings[table_name].apply_momentum(
            ids, grads, self._slot_table(params, table_name, "momentum"),
            lr, self.momentum, self.nesterov,
        )


class Adam(Optimizer):
    slot_names = ("m", "v")

    def __init__(self, learning_rate=0.001, beta_1=0.9, beta_2=0.999,
                 epsilon=1e-8, amsgrad=False):
        super().__init__(learning_rate)
        self.beta_1 = float(beta_1)
        self.beta_2 = float(beta_2)
        self.epsilon = float(epsilon)
        self.amsgrad = bool(amsgrad)
        if self.amsgrad:
            self.slot_names = ("m", "v", "max_square")

    def apply_dense(self, name, param, grad, lr):
        m = self._slot(name, "m", param.shape)
        v = self._slot(name, "v", param.shape)
        maxsq = (
            self._slot(name, "max_square", param.shape)
            if self.amsgrad else None
        )
        nk.adam(param, grad, m, v, lr, self.step, self.beta_1,
                self.beta_2, self.epsilon, max_square=maxsq)

    def apply_sparse(self, params, table_name, ids, grads, lr):
        params.embeddings[table_name].apply_adam(
            ids, grads,
            self._slot_table(params, table_name, "m"),
            self._slot_table(params, table_name, "v"),
            lr, self.step, self.beta_1, self.beta_2, self.epsilon,
            maxsq_table=(
                self._slot_table(params, table_name, "max_square")
                if self.amsgrad else None
            ),
        )


class Adagrad(Optimizer):
    slot_names = ("accumulator",)

    def __init__(self, learning_rate=0.01, epsilon=1e-8):
        super().__init__(learning_rate)
        self.epsilon = float(epsilon)

    def apply_dense(self, name, param, grad, lr):
        accum = self._slot(name, "accumulator", param.shape)
        nk.adagrad(param, grad, accum, lr, self.epsilon)

    def apply_sparse(self, params, table_name, ids, grads, lr):
        params.embeddings[table_name].apply_adagrad(
            ids, grads,
            self._slot_table(params, table_name, "accumulator"),
            lr, self.epsilon,
        )


_OPTIMIZERS = {
    "sgd": SGD,
    "momentum": Momentum,
    "adam": Adam,
    "adagrad": Adagrad,
}


def create_optimizer(opt_type, opt_args=""):
    """Build from flag strings (reference go optimizer.go:329-390)."""
    if opt_type not in _OPTIMIZERS:
        raise ValueError(
            "unknown optimizer %r (have %s)"
            % (opt_type, sorted(_OPTIMIZERS))
        )
    kwargs = parse_opt_args(opt_args) if opt_args else {}
    if "nesterov" in kwargs:
        kwargs["nesterov"] = str(kwargs["nesterov"]).lower() in (
            "true", "1", "1.0",
        )
    if "amsgrad" in kwargs:
        kwargs["amsgrad"] = str(kwargs["amsgrad"]).lower() in (
            "true", "1", "1.0",
        )
    return _OPTIMIZERS[opt_type](**kwargs)
