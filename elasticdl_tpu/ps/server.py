"""Parameter-server process (parity:
elasticdl/python/ps/parameter_server.py:35-161,
go/cmd/elasticdl_ps/main.go:27-74).

Every start establishes a monotone restart GENERATION (persisted beside
the checkpoints, and/or hinted by the launcher's ``--generation``); the
servicer stamps it on every data-plane response so workers detect a
relaunch and reconcile instead of training against a silently
rolled-back shard (docs/ps_recovery.md)."""

import os
import signal
import threading

from elasticdl_tpu.proto import rpc
from elasticdl_tpu.ps.optimizer import create_optimizer
from elasticdl_tpu.ps.parameters import Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.utils import grpc_utils, tracing
from elasticdl_tpu.utils.args import parse_ps_args
from elasticdl_tpu.utils.checkpoint import CheckpointSaver
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def establish_generation(checkpoint_dir, ps_id, hint=0):
    """Monotone restart generation for this shard, bumped on EVERY
    start.  With a checkpoint dir the counter persists in
    ``<dir>/generation-<ps_id>`` (written durably BEFORE the shard
    serves, so no response can carry a generation a crash could
    reissue); the launcher's ``hint`` (PSManager passes its per-shard
    launch count) can only move it forward.  Without either there is
    nothing to fence against and the generation is a constant 1 —
    fencing needs a persisted counter or a counting launcher."""
    persisted = 0
    path = None
    if checkpoint_dir:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "generation-%d" % ps_id)
        try:
            with open(path, "r") as f:
                persisted = int(f.read().strip() or 0)
        except (OSError, ValueError):
            persisted = 0
    generation = max(persisted + 1, int(hint or 0), 1)
    if path is not None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("%d\n" % generation)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # fsync the DIRECTORY too: the rename itself must be durable
        # before this generation stamps any response, or a power cut
        # could resurrect the old counter and let a future start
        # reissue this incarnation's generation.
        dirfd = os.open(checkpoint_dir, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    return generation


class ParameterServer:
    def __init__(self, args, master_client=None):
        self.args = args
        self._master_client = master_client
        self.parameters = Parameters()
        self.optimizer = create_optimizer(args.opt_type, args.opt_args)
        self.generation = establish_generation(
            args.checkpoint_dir or args.checkpoint_dir_for_init,
            args.ps_id, hint=getattr(args, "generation", 0),
        )
        # Identity now carries the incarnation: "[ps-0@g2]" log lines
        # and generation-stamped flight-recorder events make a relaunch
        # attributable at a glance in interleaved drill logs.
        tracing.configure_identity("ps", rank=args.ps_id,
                                   generation=self.generation)
        tracing.event("ps.generation_established",
                      generation=self.generation)
        logger.info("PS shard %d starting as generation %d",
                    args.ps_id, self.generation)
        saver = None
        if args.checkpoint_dir:
            saver = CheckpointSaver(
                args.checkpoint_dir, keep_max=args.keep_checkpoint_max
            )
        self.servicer = PserverServicer(
            self.parameters,
            self.optimizer,
            ps_id=args.ps_id,
            num_ps=args.num_ps,
            use_async=args.use_async,
            grads_to_wait=args.grads_to_wait,
            sync_version_tolerance=args.sync_version_tolerance,
            lr_staleness_modulation=args.lr_staleness_modulation,
            checkpoint_saver=saver,
            checkpoint_steps=args.checkpoint_steps,
            evaluation_steps=args.evaluation_steps,
            master_client=master_client,
            generation=self.generation,
        )
        self._server = None
        self.port = None
        self._done = threading.Event()
        if args.checkpoint_dir_for_init:
            self._restore(args.checkpoint_dir_for_init)

    def _restore(self, ckpt_dir):
        """Restore this shard from the newest COMMITTED (cross-shard
        consistent) checkpoint version, re-hash-routing if the shard
        count changed (reference go/pkg/ps/checkpoint.go:98-133;
        barrier semantics: docs/ps_recovery.md).  A shard with no
        committed checkpoint re-enters the uninitialized state and the
        workers' push-to-init path re-seeds it mid-run."""
        saver = CheckpointSaver(ckpt_dir)
        try:
            dense, embeddings, version = saver.load_shard(
                None, self.args.ps_id, self.args.num_ps
            )
        except FileNotFoundError as e:
            logger.warning("no checkpoint to restore in %s (%s); "
                           "awaiting worker push-to-init", ckpt_dir, e)
            return
        # Rollback truncation: files this shard wrote AFTER the version
        # being restored belong to the dead incarnation's abandoned
        # timeline — left in place one could later complete a label into
        # a fake "committed" set that mixes timelines.
        saver.truncate_shard_after(
            version, self.args.ps_id, self.args.num_ps
        )
        slot_payload = {
            k[len("optslot/"):]: dense.pop(k)
            for k in [k for k in dense if k.startswith("optslot/")]
        }
        if slot_payload:
            self.optimizer.restore_slots_from_payload(slot_payload)
        infos = [
            {"name": n, "dim": v[1].shape[1]}
            for n, v in embeddings.items()
            if not n.startswith("slot:") and len(v[1])
        ]
        self.parameters.restore_from_checkpoint_payload(
            dense, embeddings, infos,
            slot_names=self.optimizer.slot_names,
        )
        self.parameters.version = version
        self.servicer.seed_durable_version(version)
        logger.info("restored PS shard %d from version %d",
                    self.args.ps_id, version)

    def prepare(self):
        interceptors = []
        if getattr(self.args, "rpc_delay_ms", 0) > 0:
            # Bench rigs run worker and PS on one host; this emulates
            # the cross-host wire latency the overlap path is built
            # to hide (see bench_ps_wire.py).
            interceptors.append(grpc_utils.RpcDelayInterceptor(
                self.args.rpc_delay_ms / 1000.0
            ))
        if getattr(self.args, "rpc_fault_spec", ""):
            # Deterministic fault drills (docs/master_recovery.md):
            # script "every Nth push fails" / "shard dark for 5 s"
            # reproducibly against the worker retry paths.
            logger.warning(
                "PS RPC fault injection armed: %s",
                self.args.rpc_fault_spec,
            )
            interceptors.append(grpc_utils.FaultInjectionInterceptor(
                self.args.rpc_fault_spec
            ))
        interceptors = interceptors or None
        self._server = grpc_utils.build_server(
            max_workers=64, interceptors=interceptors
        )
        rpc.add_pserver_servicer(self.servicer, self._server)
        self.port = self._server.add_insecure_port(
            "[::]:%d" % self.args.port
        )
        self._server.start()
        logger.info("PS %d/%d listening on port %d",
                    self.args.ps_id, self.args.num_ps, self.port)
        if getattr(self.args, "status_port", -1) >= 0:
            from elasticdl_tpu.master.status_server import (
                HttpStatusServer,
            )
            from elasticdl_tpu.utils.prom import ps_to_prometheus
            from elasticdl_tpu.utils.slo import slo_section

            def collect():
                status = {
                    "ps_id": self.args.ps_id,
                    "num_ps": self.args.num_ps,
                    "version": self.parameters.version,
                    "generation": self.generation,
                    "durable_version": self.servicer.durable_version,
                    "initialized": self.parameters.initialized,
                    "counters": dict(self.servicer.counters),
                    # Per-encoding data-plane byte accounting (frame
                    # vs pb payload + decode-copy bytes) — the
                    # frame-wire bench's server-side artifact.
                    "wire": dict(self.servicer.wire_counters),
                    # Push/pull handle-time histograms: rendered
                    # natively by utils/prom.ps_to_prometheus (the one
                    # renderer home — the inline renderer that used to
                    # live here moved there with them).
                    "hists": self.servicer.timing.histograms(),
                }
                slo = slo_section()
                if slo is not None:
                    status["slo"] = slo
                return status

            self._status_server = HttpStatusServer(collect,
                                                   ps_to_prometheus,
                                                   port=self.args.
                                                   status_port)
            self._status_server.start()
        if self._master_client is not None:
            # Self-terminate when the master goes away (reference: the Go
            # PS polls the master pod every 30s, k8s_client.go:42-60) so
            # orphaned PS shards never outlive their job.
            threading.Thread(
                target=self._watch_master, name="master-watch",
                daemon=True,
            ).start()

    def _watch_master(self, poll_secs=30, max_misses=3):
        misses = 0
        while not self._done.is_set() and misses < max_misses:
            self._done.wait(poll_secs)
            if self._done.is_set():
                return
            try:
                self._master_client.get_comm_rank()
                misses = 0
            except Exception:  # noqa: BLE001
                misses += 1
        if misses >= max_misses:
            logger.info("master unreachable; PS shutting down")
            self.stop()

    def run(self):
        self._done.wait()
        self.stop()

    def stop(self, checkpoint=False):
        if checkpoint:
            # Graceful preemption (SIGTERM): persist the shard's
            # CURRENT state — params, embedding tables, optimizer
            # slots — so the relaunched shard resumes this exact
            # version instead of the last periodic save.
            try:
                self.servicer.checkpoint_now()
            except Exception as e:  # noqa: BLE001 — best effort under
                # a kill deadline
                logger.error("preemption checkpoint failed: %s", e)
        self._done.set()
        if getattr(self, "_status_server", None) is not None:
            self._status_server.stop()
            self._status_server = None
        if self._server is not None:
            self._server.stop(grace=1)
            self._server = None


def main(argv=None):
    args = parse_ps_args(argv)
    master_client = None
    if args.master_addr:
        from elasticdl_tpu.utils.retry import RetryPolicy
        from elasticdl_tpu.worker.master_client import MasterClient

        channel = grpc_utils.build_channel(args.master_addr)
        # SHORT budget: report_version runs inline on the gradient-push
        # path (after the update lock is released but before the push
        # RPC returns) — a master mid-restart should be ridden out for
        # a few seconds, never stall pushes for the full worker-side
        # outage budget.  _report_version swallows the final failure.
        master_client = MasterClient(
            channel, worker_id=-1, addr=args.master_addr,
            retry=RetryPolicy(
                name="ps_master_rpc", max_attempts=4,
                deadline_secs=5.0, base_delay_secs=0.2,
                max_delay_secs=1.0,
            ),
        )
    ps = ParameterServer(args, master_client=master_client)
    ps.prepare()
    # Operator SLO rules from the environment (ELASTICDL_SLO_SPEC,
    # e.g. "p99(ps.push_handle) < 0.02") resolve against the
    # servicer's handle-time histograms.
    from elasticdl_tpu.utils import slo as slo_mod

    slo_mod.default_watchdog().bind_timing(ps.servicer.timing)
    slo_mod.default_watchdog().arm_from_env()
    signal.signal(signal.SIGTERM, lambda *a: ps.stop(checkpoint=True))
    # AFTER the graceful-checkpoint hook: SIGTERM dumps the flight
    # recorder first, then runs the checkpoint-and-stop chain.
    tracing.arm_crash_dump()
    ps.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
