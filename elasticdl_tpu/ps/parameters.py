"""Parameter-server state: dense params + native embedding tables.

Parity with elasticdl/python/ps/parameters.py:30-224 and the Go model store
(go/pkg/ps/model.go:25-110), with the embedding rows living in the C++
store (native/kernels.cc) rather than Python dicts.
"""

import threading

import numpy as np

from elasticdl_tpu.native.bindings import NativeEmbeddingTable
from elasticdl_tpu.utils import hashing, tensor_codec
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def slot_table_name(layer_name, slot_name):
    return "%s-%s" % (layer_name, slot_name)


class Parameters:
    def __init__(self):
        self.version = 0
        self.initialized = False
        self.dense = {}             # name -> np.float32 array
        self.embeddings = {}        # name -> NativeEmbeddingTable
        self.embedding_infos = {}   # name -> info dict
        self.slot_tables = {}       # slot table name -> NativeEmbeddingTable
        # RLock: init_from_model_pb holds it across set_embedding_infos.
        # This lock makes Parameters internally consistent (init,
        # restore, checkpoint payload, table registry); per-row
        # embedding traffic stays on the native tables' rw-lock, and
        # gradient APPLIES are serialized one level up by the
        # servicer's lock (ps/servicer.py).
        self._lock = threading.RLock()

    # -- init ---------------------------------------------------------------

    def init_from_model_pb(self, model_pb):
        """First worker push initializes the shard (reference
        go/pkg/ps/server.go:209-221)."""
        with self._lock:
            if self.initialized:
                return False
            dense, embeddings, infos, version = tensor_codec.pb_to_model(
                model_pb
            )
            for name, arr in dense.items():
                self.dense[name] = np.array(arr, np.float32, copy=True)
            self.set_embedding_infos(infos)
            for name, (values, ids) in embeddings.items():
                self.embeddings[name].set(ids, values)
            self.version = max(self.version, version)
            self.initialized = True
            logger.info(
                "parameters initialized: %d dense, %d embedding tables",
                len(self.dense), len(self.embeddings),
            )
            return True

    def set_embedding_infos(self, infos):
        with self._lock:
            self._set_embedding_infos_locked(infos)

    def _set_embedding_infos_locked(self, infos):
        for info in infos:
            name = info["name"]
            if name in self.embeddings:
                continue
            self.embedding_infos[name] = info
            initializer = info.get("initializer", "uniform")
            kwargs = {}
            if initializer.startswith("constant("):
                kwargs = {"init_a": float(initializer[9:-1])}
                initializer = "constant"
            elif initializer == "uniform":
                kwargs = {"init_a": -0.05, "init_b": 0.05}
            elif initializer == "normal":
                kwargs = {"init_a": 0.0, "init_b": 0.05}
            # Stable hash, NOT builtin hash(): str hashing is
            # randomized per process, which made lazy-row init differ
            # across shard restarts (and made same-seed runs
            # irreproducible across PS processes).
            self.embeddings[name] = NativeEmbeddingTable(
                info["dim"], initializer,
                seed=hashing.string_to_id(name, 0x10000),
                **kwargs,
            )

    def create_slot_tables(self, slot_names):
        """Per-slot shadow tables (reference
        python/ps/parameters.py:169-183): zeros-initialized, same dim."""
        with self._lock:
            for name, table in self.embeddings.items():
                for slot in slot_names:
                    key = slot_table_name(name, slot)
                    if key not in self.slot_tables:
                        self.slot_tables[key] = NativeEmbeddingTable(
                            table.dim, "zeros"
                        )

    # -- access -------------------------------------------------------------

    def get_dense(self):
        # Returned by reference, deliberately without this class's
        # lock (which would synchronize nothing here): values are
        # updated in place by the optimizer under the SERVICER lock,
        # and callers iterate under that same lock (see the elastic-
        # lint baseline entry).
        return self.dense

    def pull_embedding_vectors(self, name, ids):
        # Only the registry lookup needs the lock; the row reads run
        # concurrently on the native table's rw-lock (the hot RPC must
        # not serialize behind init/restore/checkpoint).
        with self._lock:
            table = self.embeddings[name]
        if np.size(ids) == 0:
            # Preserve the row dim on empty pulls — (0, 0) breaks
            # downstream shape assumptions (worker padding, concat).
            return np.zeros((0, table.dim), np.float32)
        return table.get(ids)

    def lookup_embedding_rows(self, name, ids, default=0.0):
        """Read-only variant of :meth:`pull_embedding_vectors` for the
        SERVING lookup path: absent ids come back as ``default`` rows
        and are never lazily initialized, so serving traffic (arbitrary
        ids from the internet) cannot grow the training table or
        perturb its id set.  Same per-row atomicity as the training
        pull (the native table's rw-lock); runs entirely under the
        shared lock, so lookups never serialize behind each other."""
        with self._lock:
            table = self.embeddings[name]
        if np.size(ids) == 0:
            return np.zeros((0, table.dim), np.float32)
        rows, _found = table.get_ro(ids, default=default)
        return rows

    def to_checkpoint_payload(self):
        with self._lock:
            dense = {k: v.copy() for k, v in self.dense.items()}
            embeddings = {}
            for name, table in self.embeddings.items():
                ids, values = table.export()
                embeddings[name] = (ids, values)
            for name, table in self.slot_tables.items():
                ids, values = table.export()
                embeddings["slot:" + name] = (ids, values)
            return dense, embeddings

    def restore_from_checkpoint_payload(self, dense, embeddings, infos,
                                        slot_names=()):
        # Whole restore is one critical section (reentrant into
        # set_embedding_infos / create_slot_tables): a pull racing a
        # relaunched shard's restore must see all-or-nothing.
        with self._lock:
            for name, arr in dense.items():
                self.dense[name] = np.array(arr, np.float32, copy=True)
            self.set_embedding_infos(infos)
            for name, (ids, values) in embeddings.items():
                if name.startswith("slot:") or not len(ids):
                    continue
                if name in self.embeddings:
                    self.embeddings[name].set(ids, values)
            # Recreate optimizer slot tables, then restore their saved
            # rows — a relaunched shard must resume Adam/Momentum
            # state, not crash on the first sparse push.
            self.create_slot_tables(slot_names)
            for name, (ids, values) in embeddings.items():
                if not name.startswith("slot:") or not len(ids):
                    continue
                key = name[len("slot:"):]
                if key not in self.slot_tables:
                    self.slot_tables[key] = NativeEmbeddingTable(
                        values.shape[1], "zeros"
                    )
                self.slot_tables[key].set(ids, values)
            self.initialized = bool(self.dense) or bool(self.embeddings)
