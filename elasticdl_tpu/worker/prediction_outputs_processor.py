"""Prediction output handling (parity:
elasticdl/python/worker/prediction_outputs_processor.py:17-35)."""

import abc
import os
import threading

import numpy as np


class BasePredictionOutputsProcessor(abc.ABC):
    @abc.abstractmethod
    def process(self, predictions, worker_id):
        """Called per prediction minibatch with the model outputs."""


class NpzPredictionWriter(BasePredictionOutputsProcessor):
    """Accumulates prediction batches and writes one .npz per worker."""

    def __init__(self, output_dir):
        self.output_dir = output_dir
        self._chunks = []
        self._lock = threading.Lock()
        os.makedirs(output_dir, exist_ok=True)

    def process(self, predictions, worker_id):
        with self._lock:
            self._chunks.append(np.asarray(predictions))
            self._worker_id = worker_id

    def flush(self):
        with self._lock:
            if not self._chunks:
                return None
            out = np.concatenate(self._chunks)
            path = os.path.join(
                self.output_dir,
                "predictions-worker-%d.npz" % self._worker_id,
            )
            with open(path, "wb") as f:
                np.savez(f, predictions=out)
            self._chunks = []
        return path
