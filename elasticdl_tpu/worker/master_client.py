"""Worker-side client to the master control plane.

Parity with elasticai_api/common/master_client.py:20-131: thin typed
wrappers over the gRPC stub, constructed from env
(``MASTER_ADDR``/``WORKER_ID``) or explicitly.

Every RPC rides out a transiently-unavailable master through the
shared retry policy (utils/retry.py): a master SIGKILLed mid-job and
relaunched with ``--journal_dir`` comes back in seconds, and clients
that would previously crash (killing the worker and burning a task
retry) now reconnect and continue.  Replay safety is the server's job:
task reports carry task ids the restarted master deduplicates against
its journal, so a retried TASK report is idempotent, never
double-counted.  Progress counters (``report_batch_done``) carry no
dedup token — a retried count whose first attempt was processed can
inflate the observability counters; task accounting stays exact
(docs/master_recovery.md, "Known at-least-once edges").
"""

import json
import os
import threading
import time
from collections import deque

import numpy as np

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.proto.rpc import MasterStub
from elasticdl_tpu.utils import grpc_utils, tensor_codec
from elasticdl_tpu.utils.retry import master_rpc_policy


class MasterClient:
    def __init__(self, channel, worker_id=0, worker_host=None,
                 retry=None, addr=None):
        """``retry``: a utils.retry.RetryPolicy; None installs the
        default master outage-riding policy.  Callers on a latency
        budget (the PS push path reports versions inline) pass a short
        one; ``retry_policy.timing`` is settable afterwards so the
        Worker can bind its reported Timing onto the counters.

        ``addr``: the master address, when known.  It arms channel
        REBUILD on retry: after the master is SIGKILLed, the live
        channel's subchannel can wedge (stale connect backoff /
        poisoned fd never reaching the restarted listener), so each
        retry reconnects on a fresh channel — the first retry after
        the master is back succeeds immediately."""
        self._channel = channel
        self._stub = MasterStub(channel)
        self._addr = addr
        # One client is shared across threads (record-index fetcher,
        # deferred report flush, the main task loop), so rebuilds are
        # serialized and generation-counted: the first thread to notice
        # the outage rebuilds, later threads adopt its fresh stub.
        self._refresh_lock = threading.Lock()
        self._gen = 0
        # Retired channels are parked, NOT closed: channel.close()
        # terminates every in-flight RPC on it with CANCELLED — a code
        # the retry policy rightly refuses to retry — so another
        # thread's concurrent call would crash in exactly the outage
        # the retry machinery rides out.  Entries are (channel,
        # retired_at) and a channel is only closed once it has been
        # parked longer than _RETIRE_AGE_SECS — a count bound alone
        # is not safe: one fast-failing retry loop can cycle the
        # deque in seconds while a blackholed peer still holds
        # another thread's RPC in flight on the oldest channel.
        self._retired = deque()
        self._last_rebuild = 0.0
        self.retry_policy = retry if retry is not None else (
            master_rpc_policy()
        )
        self.worker_id = worker_id
        self.worker_host = worker_host or "worker-%d" % worker_id
        # Multi-tenant scheduler handshake (docs/scheduler.md): the
        # master's get_task response names the job this worker is
        # assigned to (0 = single-job master) and, when the assignment
        # changed, carries the job's worker config.  Subsequent RPCs
        # echo job_id so reports route to the owning job even across a
        # re-assignment.  Written only on the task-loop thread (the
        # one that calls get_task).
        self.job_id = 0
        self.job_config = None

    @classmethod
    def from_env(cls):
        addr = os.environ["MASTER_ADDR"]
        worker_id = int(os.environ.get("WORKER_ID", 0))
        channel = grpc_utils.build_channel(addr)
        grpc_utils.connect_to_master(channel, addr)
        return cls(channel, worker_id=worker_id, addr=addr)

    # A parked channel may only be closed after this long: older than
    # any plausible in-flight RPC on it (the outage-riding deadline
    # budget is 120 s by default).
    _RETIRE_AGE_SECS = 150.0
    # Floor between rebuilds: one wedged channel needs ONE fresh
    # replacement, not one per backoff step of every retrying thread —
    # without a floor, a single fast-failing retry loop mints channels
    # faster than parked ones can age out.
    _REBUILD_INTERVAL_SECS = 2.0

    def _refresh_stub(self, method_name, state):
        """Rebuild the channel (see ``addr`` in __init__) and return
        the fresh stub method for the retry loop; None (no rebuild)
        when the address is unknown.  ``state['gen']`` is the
        generation this caller last saw: if another thread already
        rebuilt past it, no second rebuild — adopt the fresh stub."""
        if self._addr is None:
            return None
        with self._refresh_lock:
            now = time.monotonic()
            if (
                state["gen"] == self._gen
                and now - self._last_rebuild >= self._REBUILD_INTERVAL_SECS
            ):
                self._retired.append((self._channel, now))
                while self._retired and (
                    now - self._retired[0][1] > self._RETIRE_AGE_SECS
                ):
                    old, _ = self._retired.popleft()
                    try:
                        old.close()
                    except Exception:  # noqa: BLE001 — already broken
                        pass
                self._channel = grpc_utils.build_channel(self._addr)
                self._stub = MasterStub(self._channel)
                self._gen += 1
                self._last_rebuild = now
            state["gen"] = self._gen
            return getattr(self._stub, method_name)

    def _call(self, rpc_fn, request, method_name, state):
        return self.retry_policy.call(
            rpc_fn, request, description=method_name,
            refresh=lambda: self._refresh_stub(method_name, state),
        )

    def get_task(self, task_type=None):
        req = pb.GetTaskRequest(worker_id=self.worker_id,
                                job_id=self.job_id)
        if task_type is not None:
            req.task_type = task_type
        # Snapshot the (stub, generation) pair coherently under the
        # refresh lock — a racing rebuild can't hand this call a torn
        # (old stub, new gen) pair — then RPC outside it.
        with self._refresh_lock:
            stub = self._stub
            state = {"gen": self._gen}
        res = self._call(stub.get_task, req, "get_task", state)
        if res.job_id and res.job_id != self.job_id:
            # Re-assignment handshake: adopt the new job identity; the
            # Worker loop reads job_config and rebuilds its pipeline
            # before processing the first task of the new job.
            self.job_id = res.job_id
            if res.job_config:
                self.job_config = json.loads(res.job_config)
        return res.task

    def report_task_result(self, task_id, err_message="", exec_counters=None,
                           requeue=False, job_id=None):
        """``job_id``: the OWNING job of ``task_id`` (task ids are only
        unique per job under the multi-tenant scheduler); defaults to
        the current assignment — callers that report after a
        re-assignment pass the task's job explicitly."""
        req = pb.ReportTaskResultRequest(
            task_id=task_id, err_message=err_message, requeue=requeue,
            job_id=self.job_id if job_id is None else job_id,
        )
        for k, v in (exec_counters or {}).items():
            req.exec_counters[k] = int(v)
        with self._refresh_lock:
            stub = self._stub
            state = {"gen": self._gen}
        self._call(
            stub.report_task_result, req, "report_task_result", state
        )

    def report_batch_done(self, record_count, telemetry=None,
                          job_id=None):
        """``telemetry``: optional dict of live training health
        piggybacked on the progress report (docs/observability.md) —
        keys matching the ReportBatchDoneRequest telemetry fields
        (steps_per_sec, sync_fraction, push_staleness, window_size,
        steps_done); unknown keys are ignored.  ``job_id``: the job
        these records/telemetry belong to (defaults to the current
        assignment) — keys the master's per-job aggregate so shared-
        pool jobs never collide."""
        req = pb.ReportBatchDoneRequest(
            worker_id=self.worker_id, record_count=record_count,
            job_id=self.job_id if job_id is None else job_id,
        )
        for field in ("steps_per_sec", "sync_fraction",
                      "push_staleness", "window_size"):
            value = (telemetry or {}).get(field)
            if value is not None:
                setattr(req, field, float(value))
        steps_done = (telemetry or {}).get("steps_done")
        if steps_done is not None:
            req.steps_done = int(steps_done)
        hist_delta = (telemetry or {}).get("hist_delta")
        if hist_delta:
            # Sparse step-time histogram delta (utils/hist.py): the
            # master merges these exactly into per-worker/per-job
            # distributions — the percentile-plane piggyback.
            req.hist_delta = hist_delta
        with self._refresh_lock:
            stub = self._stub
            state = {"gen": self._gen}
        self._call(stub.report_batch_done, req, "report_batch_done", state)

    def get_comm_rank(self):
        req = pb.GetCommRankRequest(worker_host=self.worker_host,
                                    job_id=self.job_id)
        with self._refresh_lock:
            stub = self._stub
            state = {"gen": self._gen}
        return self._call(stub.get_comm_rank, req, "get_comm_rank", state)

    def report_train_loop_status(self, status, job_id=None):
        """``job_id``: which job's world to join/leave — a drained
        worker LOOP_ENDs its OLD job during the re-assignment
        handshake; defaults to the current assignment."""
        req = pb.ReportTrainLoopStatusRequest(
            worker_host=self.worker_host, status=status,
            job_id=self.job_id if job_id is None else job_id,
        )
        with self._refresh_lock:
            stub = self._stub
            state = {"gen": self._gen}
        self._call(
            stub.report_train_loop_status, req,
            "report_train_loop_status", state,
        )

    def report_evaluation_metrics(self, model_outputs, labels,
                                  model_version=-1):
        req = pb.ReportEvaluationMetricsRequest(
            worker_id=self.worker_id, model_version=model_version,
            job_id=self.job_id,
        )
        if isinstance(model_outputs, dict):
            for name, arr in model_outputs.items():
                tensor_codec.ndarray_to_pb(
                    np.asarray(arr), out=req.model_outputs[name]
                )
        else:
            tensor_codec.ndarray_to_pb(
                np.asarray(model_outputs), out=req.model_outputs["output"]
            )
        tensor_codec.ndarray_to_pb(np.asarray(labels), out=req.labels)
        with self._refresh_lock:
            stub = self._stub
            state = {"gen": self._gen}
        self._call(
            stub.report_evaluation_metrics, req,
            "report_evaluation_metrics", state,
        )

    def report_version(self, version, ps_id=None, generation=0,
                       durable_version=0):
        """``ps_id`` (+ generation/durable_version) marks this as a PS
        shard's report: the master tracks per-shard recovery state and
        derives the coordinated-checkpoint commit mark from the
        cross-shard min of ``durable_version`` (docs/ps_recovery.md).
        Workers report plain versions and leave the PS fields unset."""
        req = pb.ReportVersionRequest(model_version=version,
                                      job_id=self.job_id)
        if ps_id is not None:
            req.is_ps = True
            req.ps_id = int(ps_id)
            req.generation = int(generation)
            req.durable_version = int(durable_version)
        with self._refresh_lock:
            stub = self._stub
            state = {"gen": self._gen}
        self._call(stub.report_version, req, "report_version", state)

    def report_training_params(self, **kwargs):
        req = pb.ReportTrainingParamsRequest(**kwargs)
        with self._refresh_lock:
            stub = self._stub
            state = {"gen": self._gen}
        self._call(
            stub.report_training_params, req,
            "report_training_params", state,
        )
