"""Worker-side client to the master control plane.

Parity with elasticai_api/common/master_client.py:20-131: thin typed
wrappers over the gRPC stub, constructed from env
(``MASTER_ADDR``/``WORKER_ID``) or explicitly.
"""

import os

import numpy as np

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.proto.rpc import MasterStub
from elasticdl_tpu.utils import grpc_utils, tensor_codec


class MasterClient:
    def __init__(self, channel, worker_id=0, worker_host=None):
        self._stub = MasterStub(channel)
        self.worker_id = worker_id
        self.worker_host = worker_host or "worker-%d" % worker_id

    @classmethod
    def from_env(cls):
        addr = os.environ["MASTER_ADDR"]
        worker_id = int(os.environ.get("WORKER_ID", 0))
        channel = grpc_utils.build_channel(addr)
        grpc_utils.wait_for_channel_ready(channel)
        return cls(channel, worker_id=worker_id)

    def get_task(self, task_type=None):
        req = pb.GetTaskRequest(worker_id=self.worker_id)
        if task_type is not None:
            req.task_type = task_type
        return self._stub.get_task(req).task

    def report_task_result(self, task_id, err_message="", exec_counters=None,
                           requeue=False):
        req = pb.ReportTaskResultRequest(
            task_id=task_id, err_message=err_message, requeue=requeue
        )
        for k, v in (exec_counters or {}).items():
            req.exec_counters[k] = int(v)
        self._stub.report_task_result(req)

    def report_batch_done(self, record_count):
        self._stub.report_batch_done(
            pb.ReportBatchDoneRequest(
                worker_id=self.worker_id, record_count=record_count
            )
        )

    def get_comm_rank(self):
        return self._stub.get_comm_rank(
            pb.GetCommRankRequest(worker_host=self.worker_host)
        )

    def report_train_loop_status(self, status):
        self._stub.report_train_loop_status(
            pb.ReportTrainLoopStatusRequest(
                worker_host=self.worker_host, status=status
            )
        )

    def report_evaluation_metrics(self, model_outputs, labels):
        req = pb.ReportEvaluationMetricsRequest(worker_id=self.worker_id)
        if isinstance(model_outputs, dict):
            for name, arr in model_outputs.items():
                tensor_codec.ndarray_to_pb(
                    np.asarray(arr), out=req.model_outputs[name]
                )
        else:
            tensor_codec.ndarray_to_pb(
                np.asarray(model_outputs), out=req.model_outputs["output"]
            )
        tensor_codec.ndarray_to_pb(np.asarray(labels), out=req.labels)
        self._stub.report_evaluation_metrics(req)

    def report_version(self, version):
        self._stub.report_version(pb.ReportVersionRequest(model_version=version))

    def report_training_params(self, **kwargs):
        self._stub.report_training_params(
            pb.ReportTrainingParamsRequest(**kwargs)
        )
