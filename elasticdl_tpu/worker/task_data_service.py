"""Turns the master's task stream into batches of ndarrays.

Parity with elasticdl/python/worker/task_data_service.py:24-134, minus
tf.data: records stream from the data reader, the zoo's ``feed`` packs them
into numpy batches sized for the jitted step.
"""


class TaskDataService:
    def __init__(self, data_reader, feed_fn):
        self._reader = data_reader
        self._feed = feed_fn

    def record_stream(self, task):
        return self._reader.read_records(task)

    def batch_stream(self, task, batch_size):
        """Yield (features, labels, record_count) batches for one task."""
        buffer = []
        for record in self._reader.read_records(task):
            buffer.append(record)
            if len(buffer) == batch_size:
                features, labels = self._feed(buffer)
                yield features, labels, len(buffer)
                buffer = []
        if buffer:
            features, labels = self._feed(buffer)
            yield features, labels, len(buffer)
