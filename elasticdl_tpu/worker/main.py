"""Worker entrypoint (parity: elasticdl/python/worker/main.py:26-62).

Identity and topology arrive via env (``MASTER_ADDR``, ``WORKER_ID``) with
flag overrides; the model comes from the zoo contract by module name.
"""

import os

if os.environ.get("ELASTICDL_TPU_PLATFORM"):
    # The session sitecustomize may have force-selected a TPU backend via
    # jax.config (overriding JAX_PLATFORMS); honor an explicit platform
    # request before any backend is initialized.  Process-backend drills
    # set this to "cpu" so N workers can share one host.
    import jax

    jax.config.update(
        "jax_platforms", os.environ["ELASTICDL_TPU_PLATFORM"]
    )

from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.utils import grpc_utils, tracing
from elasticdl_tpu.utils.args import parse_worker_args
from elasticdl_tpu.utils.checkpoint import CheckpointSaver
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker

logger = get_logger(__name__)


def resolve_worker_id(args):
    """Flag wins, env fallback — the ONE resolution both the identity
    label and the MasterClient registration use (they must never name
    different workers)."""
    return (
        args.worker_id if args.worker_id >= 0
        else int(os.environ.get("WORKER_ID", 0))
    )


def _build_collective_trainer(args, mc, spec, worker_id,
                              batch_size=None, checkpoint_dir=None,
                              checkpoint_steps=None, seed=None,
                              mesh=None):
    """The ONE CollectiveTrainer construction path — shared by the
    eager launch build and the multi-tenant job-switch factory, so a
    rebuilt worker can never silently train with different settings
    (checkpoint rules, bf16, zero1, version-report cadence) than a
    freshly launched one.  The keyword overrides are the job-config
    fields; everything unset falls back to the launch args."""
    batch_size = (
        args.batch_size if batch_size is None else int(batch_size)
    )
    checkpoint_dir = (
        args.checkpoint_dir if checkpoint_dir is None
        else checkpoint_dir
    )
    checkpoint_steps = (
        args.checkpoint_steps if checkpoint_steps is None
        else int(checkpoint_steps)
    )
    seed = args.seed if seed is None else int(seed)
    saver = None
    if checkpoint_dir:
        saver = CheckpointSaver(
            checkpoint_dir, keep_max=args.keep_checkpoint_max
        )
        if worker_id != 0:
            # Every worker may restore, but only worker 0 writes (the
            # collective path replicates params, so any single copy is
            # the model).
            checkpoint_steps = 0
    exporter = None
    export_steps = getattr(args, "export_steps", 0)
    if getattr(args, "export_base", "") and export_steps:
        if worker_id != 0:
            # Same single-writer guard as checkpointing: params are
            # replicated, so worker 0's exports ARE the model.
            export_steps = 0
        else:
            from elasticdl_tpu.serving.export import ContinuousExporter

            exporter = ContinuousExporter(
                args.export_base, model_name=args.job_name,
                wire_format=getattr(args, "export_wire", "npz"),
            )
    trainer = CollectiveTrainer(
        spec,
        batch_size=batch_size,
        mesh=mesh,
        master_client=mc,
        report_version_steps=max(1, args.evaluation_steps // 4)
        if args.evaluation_steps else 0,
        checkpoint_saver=saver,
        checkpoint_steps=checkpoint_steps,
        use_bf16_compute=args.use_bf16,
        rng_seed=seed,
        zero1=args.zero1,
        exporter=exporter,
        export_steps=export_steps,
    )
    if saver is not None:
        trainer.init_from_checkpoint()
    return trainer


def _job_context_factory(args, mc):
    """Multi-tenant pools (docs/scheduler.md): build the callable the
    Worker invokes when the scheduler re-assigns it to a different job
    — rebuilds data reader, model spec and trainer from the handshake
    config, in place, without a process restart.  Wired for
    local-strategy pool workers; collective workers keep their elastic
    controller bound to one trainer, and PS workers keep their PS
    client topology, so both adopt re-assignments as an id only."""
    if args.distribution_strategy != "local":
        return None

    worker_id = resolve_worker_id(args)

    def build(cfg):
        model_zoo = cfg.get("model_zoo", args.model_zoo)
        model_params = cfg.get("model_params", args.model_params)
        batch_size = int(cfg.get("batch_size", args.batch_size))
        records_per_task = batch_size * int(
            cfg.get("num_minibatches_per_task",
                    args.num_minibatches_per_task)
        )
        spec = load_model_spec(model_zoo, model_params=model_params)
        reader = create_data_reader(
            cfg.get("data_origin", args.data_origin),
            records_per_shard=records_per_task,
        )
        # Job state lives with the job: a worker joining a
        # checkpointed job resumes that job's trajectory, a worker
        # joining an uncheckpointed one starts from the job's seeded
        # init (tenant isolation — nothing rides over from the
        # previous job's params).
        trainer = _build_collective_trainer(
            args, mc, spec, worker_id,
            batch_size=batch_size,
            checkpoint_dir=cfg.get("checkpoint_dir"),
            checkpoint_steps=cfg.get("checkpoint_steps"),
            seed=cfg.get("seed"),
        )
        return reader, spec, trainer

    return build


def _initial_job_config(args):
    """The pool-template config this worker's eagerly-built pipeline
    corresponds to — lets the first handshake skip the rebuild when
    the assigned job matches the launch args.  Derived from the ONE
    field list the fast-path comparison uses, so the two can't
    drift."""
    return {
        field: getattr(args, field)
        for field in Worker._JOB_KEY_FIELDS
    }


def build_worker(args):
    master_addr = args.master_addr or os.environ.get("MASTER_ADDR", "")
    worker_id = resolve_worker_id(args)
    channel = grpc_utils.build_channel(master_addr)
    grpc_utils.connect_to_master(channel, master_addr)
    mc = MasterClient(channel, worker_id=worker_id, addr=master_addr)

    spec = load_model_spec(args.model_zoo,
                           model_params=args.model_params)
    records_per_task = args.batch_size * args.num_minibatches_per_task
    reader = create_data_reader(
        args.data_origin, records_per_shard=records_per_task
    )
    if args.job_type == "predict" and spec.prediction_outputs_processor \
            is None:
        from elasticdl_tpu.worker.prediction_outputs_processor import (
            NpzPredictionWriter,
        )

        spec.prediction_outputs_processor = NpzPredictionWriter(
            args.prediction_outputs
        )
    if args.distribution_strategy == "ps":
        from elasticdl_tpu.utils.retry import ps_rpc_policy
        from elasticdl_tpu.worker.ps_client import build_ps_client
        from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer

        ps_client = build_ps_client(
            args.ps_addrs, wire_dtype=args.ps_wire_dtype,
            # The pipelined trainer pushes from a background thread;
            # give that traffic its own connections so it never convoys
            # the foreground pulls.
            dedicated_push_channels=(
                args.use_async and args.async_push_window > 0
            ),
            # Outage riding (docs/ps_recovery.md): a shard SIGKILLed
            # and relaunched by PSManager on the same port is ridden
            # through per-shard retries with channel rebuild instead of
            # killing this worker.
            retry=ps_rpc_policy(),
        )
        trainer = ParameterServerTrainer(
            spec, ps_client,
            batch_size=args.batch_size,
            master_client=mc,
            rng_seed=args.seed,
            atomic_sync=not args.use_async,
            async_push_window=args.async_push_window,
            # Every dense pull drains the push pipeline; a cadence > 1
            # is what gives the async push room to overlap compute.
            get_model_steps=args.get_model_steps,
        )
        return Worker(
            mc, reader, spec, trainer,
            batch_size=args.batch_size,
            log_loss_steps=args.log_loss_steps,
            # Same driver API as the collective path; the PS trainer's
            # max_window=1 keeps it on the per-step loop (its overlap
            # lives in the async push pipeline + embedding prefetch).
            fused_steps=args.fused_steps,
            device_prefetch=args.device_prefetch,
        )
    mesh = None
    if args.distribution_strategy == "collective":
        # Shard the batch over every device this process sees (a TPU
        # worker VM sees its slice's local chips); XLA inserts the
        # gradient all-reduce over ICI.  Multi-host worlds additionally
        # join the master rendezvous (join_rendezvous below) and
        # re-initialize on membership epochs via the elastic controller.
        import jax
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()), ("data",))
    trainer = _build_collective_trainer(args, mc, spec, worker_id,
                                        mesh=mesh)
    mem = trainer.zero1_report()
    if mem is not None:
        # Startup accounting for the operator: what one device holds in
        # optimizer state under the chosen placement, and what the
        # other mode would cost (rebuild() logs the same line again on
        # every elastic re-form).
        logger.info(
            "optimizer state per device: %d bytes (%s, %d devices; "
            "replicated equivalent %d bytes, %.1fx)",
            mem["per_device_bytes"], mem["mode"], mem["num_shards"],
            mem["replicated_equiv_bytes"], mem["reduction_factor"],
        )
    elastic = None
    if args.distribution_strategy == "collective":
        # Managed elastic AllReduce: the controller consumes the
        # master's rendezvous epochs from inside the task loop — the
        # worker joins the (possibly multi-process) collective world,
        # re-forms it on membership changes, and a finished/dead peer
        # is just another epoch (docs/designs/elastic_collectives.md).
        from elasticdl_tpu.api.controller import (
            ElasticCollectiveController,
        )
        from elasticdl_tpu.parallel.distributed import (
            initialize_from_rendezvous,
        )

        def mesh_builder(rank, world_size, coordinator_addr):
            import jax
            import numpy as np
            from jax.sharding import Mesh

            initialize_from_rendezvous(
                rank, world_size, coordinator_addr)
            return Mesh(np.array(jax.devices()), ("data",))

        elastic = ElasticCollectiveController(
            mc, trainer,
            check_steps=max(1, args.num_minibatches_per_task),
            mesh_builder=mesh_builder,
        )
    worker = Worker(
        mc, reader, spec, trainer,
        batch_size=args.batch_size,
        log_loss_steps=args.log_loss_steps,
        join_rendezvous=args.distribution_strategy == "collective",
        elastic_controller=elastic,
        fused_steps=args.fused_steps,
        device_prefetch=args.device_prefetch,
        # Multi-tenant pools: rebuild the pipeline in place when the
        # scheduler re-assigns this worker to a different job.
        job_context_factory=_job_context_factory(args, mc),
        initial_job_config=_initial_job_config(args),
    )
    return worker


def main(argv=None):
    import signal

    from elasticdl_tpu.worker.worker import PREEMPTED_EXIT_CODE

    args = parse_worker_args(argv)
    # Structured process identity: every log line (and every flight-
    # recorder event) of an interleaved drill names its process.
    worker_id = resolve_worker_id(args)
    tracing.configure_identity("worker", rank=worker_id)
    logger.info("worker starting: %s", vars(args))
    worker = build_worker(args)

    def _graceful_preempt(_sig, _frame):
        # Preemptible hosts deliver SIGTERM with a grace window: finish
        # the in-flight minibatch, checkpoint, exit 143 (the manager
        # relaunches a replacement).
        logger.warning("SIGTERM received: graceful preemption")
        worker.request_stop()

    try:
        signal.signal(signal.SIGTERM, _graceful_preempt)
    except ValueError:
        pass  # not the main thread (embedded use)
    # AFTER the preemption hook so the SIGTERM chain is
    # dump-ring-then-graceful-preempt ($ELASTICDL_TRACE_DIR gates it).
    tracing.arm_crash_dump()
    if args.profile_dir:
        from elasticdl_tpu.utils.timing import device_trace

        with device_trace(args.profile_dir):
            worker.run()
    else:
        worker.run()
    if worker.preempted:
        logger.info("worker preempted (checkpointed)")
        return PREEMPTED_EXIT_CODE
    logger.info("worker done")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
