"""Parameter-server trainer — the sparse/large-embedding path.

Parity with elasticdl/python/worker/ps_trainer.py:36-440, redesigned for
XLA.  The reference routes embedding lookups through ``tf.py_function``
inside the graph (embedding_delegate.py:74-106); here the jitted step stays
*pure*: embedding rows are pulled from the PS on the host, passed into the
step as regular inputs, and the step returns gradients w.r.t. those inputs
(the reference's "BET" tape trick, done the functional way).  Static shapes
everywhere: the unique-id list is padded to the batch's id count, so one
compilation serves every batch.

Step shape:
  1. every ``get_model_steps``: pull dense params from PS (push-to-init on
     first contact, ps_trainer.py:160-177 semantics)
  2. host: collect per-table ids, unique+pad, pull rows -> [U, dim]
  3. device: jitted value_and_grad over (params, emb_rows)
  4. host: push dense grads + per-table (grad_rows[:n_unique], ids) to PS
  5. a rejected push (sync mode staleness) raises -> the worker's
     minibatch retry loop re-pulls and retries
"""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.pytree import (
    flatten_with_names,
    to_numpy,
    unflatten_from_names,
)
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.collective_trainer import _pad_batch
from elasticdl_tpu.worker.trainer import Trainer

logger = get_logger(__name__)

IDS_KEY = "__ids__"


class GradientsRejected(RuntimeError):
    """Sync-mode PS rejected a stale push; re-pull and retry."""


class ParameterServerTrainer(Trainer):
    def __init__(
        self,
        spec,
        ps_client,
        batch_size,
        master_client=None,
        get_model_steps=1,
        rng_seed=0,
        learning_rate=0.0,
        atomic_sync=False,
    ):
        self._spec = spec
        self._ps = ps_client
        self._batch_size = batch_size
        self._mc = master_client
        self._get_model_steps = get_model_steps
        self._learning_rate = learning_rate
        # Sync jobs with num_ps > 1 need the prepare/commit push so one
        # shard's stale-reject aborts the minibatch on every shard.
        self._atomic_sync = atomic_sync
        self.timing = Timing(logger=logger)

        self._params = spec.init_fn(jax.random.PRNGKey(rng_seed))
        self._emb_dims = {
            info["name"]: info["dim"]
            for info in spec.ps_embedding_infos
        }
        self._version = 0
        self._steps = 0
        self._grad_step = None
        self._example_serving_input = None
        self._eval_step = None
        self._push_model_to_init()

    # -- PS interaction -----------------------------------------------------

    def _push_model_to_init(self):
        """First contact: initialize the PS shards from the local init
        (reference server.go:209-221 push-to-init)."""
        initialized, version, dense = self._ps.pull_dense_parameters(-1)
        if not initialized:
            named, _ = flatten_with_names(to_numpy(self._params))
            self._ps.push_model(
                named, embedding_infos=self._spec.ps_embedding_infos
            )
            initialized, version, dense = self._ps.pull_dense_parameters(-1)
        if dense:
            self._merge_dense(dense)
        self._version = version

    def _pull_dense(self):
        with self.timing.timeit("get_model"):
            initialized, version, dense = self._ps.pull_dense_parameters(
                self._version
            )
            if not initialized:
                # A PS shard restarted without a restorable checkpoint:
                # re-initialize it from the local model (reference
                # test_restart_ps semantics) and continue training.
                logger.warning(
                    "PS uninitialized (restart?); re-pushing model"
                )
                self._push_model_to_init()
                return
            if dense:
                self._merge_dense(dense)
            self._version = version

    def _merge_dense(self, dense):
        """Merge a (possibly partial) dense pull into local params — a
        freshly restored shard can lag the others and return only its
        slice, or nothing at all."""
        named, _ = flatten_with_names(to_numpy(self._params))
        named.update(dense)
        self._params = unflatten_from_names(
            to_numpy(self._params), named
        )

    # -- embedding plumbing -------------------------------------------------

    def _prepare_embeddings(self, features):
        """Extract ids, pull rows, return (clean_features, emb_inputs,
        push_info)."""
        if not isinstance(features, dict) or IDS_KEY not in features:
            return features, {}, {}
        features = dict(features)
        ids_map = features.pop(IDS_KEY)
        emb_inputs = {}
        push_info = {}
        for table, ids in ids_map.items():
            ids = np.asarray(ids, dtype=np.int64)
            flat = ids.reshape(-1)
            uniq, inverse = np.unique(flat, return_inverse=True)
            n_uniq = uniq.size
            # Pull only the unique rows; pad host-side to the flat id
            # count so the jitted step sees one static shape per batch
            # size without inflating the gRPC payload.
            with self.timing.timeit("pull_embedding"):
                rows = self._ps.pull_embedding_vectors(table, uniq)
            padded_rows = np.zeros(
                (flat.size, self._emb_dims[table]), np.float32
            )
            padded_rows[:n_uniq] = rows
            features["idx__" + table] = inverse.reshape(ids.shape).astype(
                np.int32
            )
            emb_inputs[table] = padded_rows
            push_info[table] = (uniq, n_uniq)
        return features, emb_inputs, push_info

    # -- jitted steps -------------------------------------------------------

    def _build_grad_step(self):
        apply_fn = self._spec.apply_fn
        loss_fn = self._spec.loss_fn

        @jax.jit
        def grad_step(params, emb_inputs, features, labels, weights):
            def f(params, emb_inputs):
                feats = dict(features) if isinstance(features, dict) else (
                    features
                )
                if emb_inputs:
                    feats = dict(feats)
                    for table, rows in emb_inputs.items():
                        feats["emb__" + table] = rows
                out = apply_fn(params, feats, True)
                per_example = loss_fn(out, labels).astype(jnp.float32)
                per_example = per_example.reshape(
                    per_example.shape[0], -1
                ).mean(axis=-1)
                return jnp.sum(per_example * weights) / jnp.maximum(
                    jnp.sum(weights), 1.0
                )

            loss, (param_grads, emb_grads) = jax.value_and_grad(
                f, argnums=(0, 1)
            )(params, emb_inputs)
            return loss, param_grads, emb_grads

        return grad_step

    def _build_eval_step(self):
        apply_fn = self._spec.apply_fn

        @jax.jit
        def eval_step(params, emb_inputs, features):
            feats = features
            if emb_inputs:
                feats = dict(features)
                for table, rows in emb_inputs.items():
                    feats["emb__" + table] = rows
            return apply_fn(params, feats, False)

        return eval_step

    # -- Trainer API --------------------------------------------------------

    def train_minibatch(self, features, labels):
        if self._steps % self._get_model_steps == 0:
            self._pull_dense()
        # Pad BEFORE preparing embeddings so id-array shapes are static
        # across partial batches (padding rows look up id 0 with weight 0).
        (features, labels), weights = _pad_batch(
            (features, labels), self._batch_size
        )
        features, emb_inputs, push_info = self._prepare_embeddings(features)
        if self._example_serving_input is None:
            # Serving signature: feature dict with the looked-up
            # emb__<table> rows merged in, exactly what apply_fn sees.
            merged = dict(features) if emb_inputs else features
            for table, rows in (emb_inputs or {}).items():
                merged["emb__" + table] = rows
            self._example_serving_input = jax.tree_util.tree_map(
                lambda a: np.zeros(np.shape(a), np.asarray(a).dtype),
                merged,
            )
        if self._grad_step is None:
            self._grad_step = self._build_grad_step()
        with self.timing.timeit("batch_process"):
            loss, param_grads, emb_grads = self._grad_step(
                self._params, emb_inputs, features, labels, weights
            )
        with self.timing.timeit("report_gradient"):
            named_grads, _ = flatten_with_names(to_numpy(param_grads))
            emb_push = {}
            for table, (uniq_ids, n_uniq) in push_info.items():
                rows = np.asarray(emb_grads[table])[:n_uniq]
                emb_push[table] = (rows, uniq_ids)
            push = (
                self._ps.push_gradients_atomic if self._atomic_sync
                else self._ps.push_gradients
            )
            accepted, version = push(
                named_grads, emb_push,
                version=self._version,
                learning_rate=self._learning_rate,
            )
        if not accepted:
            self._pull_dense()
            raise GradientsRejected(
                "stale gradients at version %d" % self._version
            )
        # Do NOT adopt the push response's version: _version means "the
        # server version my local params correspond to", and our params
        # still predate the update we just pushed.  Claiming the newer
        # version made the next pull's `request.version < server.version`
        # check pass vacuously, so dense params went permanently stale
        # (caught by test_feature_column_feed_trains_through_ps; the
        # DeepFM tests masked it because embedding pulls aren't
        # version-gated).  _version advances only in _pull_dense.
        self._steps += 1
        return float(loss), version

    def evaluate_minibatch(self, features, labels):
        n = jax.tree_util.tree_leaves(features)[0].shape[0]
        (features, labels), _ = _pad_batch(
            (features, labels), self._batch_size
        )
        features, emb_inputs, _ = self._prepare_embeddings(features)
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        outputs = self._eval_step(self._params, emb_inputs, features)
        return np.asarray(outputs)[:n], np.asarray(labels)[:n]

    def predict_minibatch(self, features):
        outputs, _ = self.evaluate_minibatch(
            features, np.zeros((jax.tree_util.tree_leaves(features)[0]
                                .shape[0],), np.int32)
        )
        return outputs

    @property
    def version(self):
        return self._version

    def export_parameters(self):
        named, _ = flatten_with_names(to_numpy(self._params))
        return named

    def serving_bundle(self):
        """Servable over (dense params, features+emb__rows): the server
        looks embedding rows up host-side from the exported tables
        (serving/loader.py lookup_embedding) and feeds them as
        emb__<table> inputs — the PS-path analog of the reference's
        localized SavedModel (model_handler.py:171-236)."""
        if self._example_serving_input is None:
            return None
        apply_fn = self._spec.apply_fn
        return (
            lambda p, x: apply_fn(p, x, False),
            to_numpy(self._params),
            self._example_serving_input,
        )
