"""Parameter-server trainer — the sparse/large-embedding path.

Parity with elasticdl/python/worker/ps_trainer.py:36-440, redesigned for
XLA.  The reference routes embedding lookups through ``tf.py_function``
inside the graph (embedding_delegate.py:74-106); here the jitted step stays
*pure*: embedding rows are pulled from the PS on the host, passed into the
step as regular inputs, and the step returns gradients w.r.t. those inputs
(the reference's "BET" tape trick, done the functional way).  Static shapes
everywhere: the unique-id list is padded to the batch's id count, so one
compilation serves every batch.

Step shape:
  1. every ``get_model_steps``: pull dense params from PS (push-to-init on
     first contact, ps_trainer.py:160-177 semantics)
  2. host: collect per-table ids, unique+pad, pull rows -> [U, dim]
  3. device: jitted value_and_grad over (params, emb_rows)
  4. host: push dense grads + per-table (grad_rows[:n_unique], ids) to PS
  5. a rejected push (sync mode staleness) raises -> the worker's
     minibatch retry loop re-pulls and retries

Overlapped hot path (``async_push_window`` > 0, async jobs): step 4 runs
on a single-thread background executor so step N's push overlaps step
N+1's embedding pull and jitted compute — classic bounded-staleness
pipelining (at most ``async_push_window`` pushes in flight; the pipeline
drains before exceeding it).  A push the PS rejects surfaces as
``GradientsRejected`` on a later ``train_minibatch`` after the pipeline
drains, and the worker's existing re-pull/retry loop takes over.  Sync
jobs (``atomic_sync=True``) keep the strictly ordered blocking
prepare/commit push — the 2PC protocol's staleness window is the whole
point there, so nothing may ride ahead of it.  Independently,
``prefetch_embeddings`` lets the worker loop start the NEXT batch's
embedding pulls while the current device step runs (composing with
``data/parallel_reader.prefetch_batches``, which overlaps read/decode the
same way one stage earlier).

Crash-restart recovery (docs/ps_recovery.md): the PSClient tracks each
shard's restart generation; when it moves, ``_maybe_reconcile`` drops
the in-flight pipelined pushes (the restarted shard fences them — they
were stamped by the dead incarnation), invalidates prefetched embedding
rows, and re-pulls dense state unconditionally past the local-version
fast path (a crash-restore rollback leaves the server's version BELOW
ours).  A shard relaunched with no restorable checkpoint serves
uninitialized and is re-seeded mid-run via the push-to-init path.
"""

from collections import deque
from concurrent.futures import ThreadPoolExecutor

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.pytree import flatten_with_names, to_numpy
from elasticdl_tpu.utils.retry import RetryPolicy
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.collective_trainer import _pad_batch
from elasticdl_tpu.worker.fused_driver import PreparedBatch, StagedWindow
from elasticdl_tpu.worker.trainer import Trainer

logger = get_logger(__name__)

IDS_KEY = "__ids__"

# Prefetched embedding pulls kept live at once; the worker loop runs one
# batch ahead so 2 per table is already generous — the cap only guards
# against a caller prefetching far past what it trains.
PREFETCH_CACHE_MAX = 8


class GradientsRejected(RuntimeError):
    """Sync-mode PS rejected a stale push; re-pull and retry."""


class ParameterServerTrainer(Trainer):
    def __init__(
        self,
        spec,
        ps_client,
        batch_size,
        master_client=None,
        get_model_steps=1,
        rng_seed=0,
        learning_rate=0.0,
        atomic_sync=False,
        async_push_window=0,
    ):
        self._spec = spec
        self._ps = ps_client
        self._batch_size = batch_size
        self._mc = master_client
        self._get_model_steps = get_model_steps
        self._learning_rate = learning_rate
        # Sync jobs with num_ps > 1 need the prepare/commit push so one
        # shard's stale-reject aborts the minibatch on every shard.
        self._atomic_sync = atomic_sync
        # Max gradient pushes in flight behind the compute (0 =
        # serialized blocking push, the pre-pipeline behavior).
        # atomic_sync overrides this to stay strictly ordered.
        self._push_window = 0 if atomic_sync else max(
            0, int(async_push_window)
        )
        self.timing = Timing(logger=logger)
        # Shared bounded-retry policy (utils/retry.py) for the async
        # push path: by the time an async push fails its minibatch was
        # already reported done, so the ride-out must live HERE or the
        # gradient is dropped.  Any RpcError is retried (the in-task
        # retry is the last line of defense), budget = 6 attempts.
        self._push_retry = RetryPolicy(
            name="ps_push",
            max_attempts=6,
            deadline_secs=None,
            base_delay_secs=0.1,
            max_delay_secs=3.0,
            retryable=lambda e: isinstance(e, grpc.RpcError),
            timing=self.timing,
        )
        # The PSClient is built before this trainer owns a Timing; bind
        # it so its outage-riding retry counters (rpc_retry/rpc_gaveup)
        # land in the same reported set.
        ps_retry = getattr(ps_client, "retry_policy", None)
        if ps_retry is not None and ps_retry.timing is None:
            ps_retry.timing = self.timing

        # Single worker thread => pushes leave in submission order
        # (double-buffered, not reordered); created eagerly so the
        # shutdown story lives in close() regardless of window config.
        self._push_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ps-push"
        )
        self._push_inflight = deque()   # futures, oldest first
        self._prefetch_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="emb-prefetch"
        )
        self._prefetched = {}   # (table, uniq-ids bytes) -> Future[rows]
        self._prefetch_active = False

        self._params = spec.init_fn(jax.random.PRNGKey(rng_seed))
        self._emb_dims = {
            info["name"]: info["dim"]
            for info in spec.ps_embedding_infos
        }
        # The param pytree's structure never changes, so flatten once:
        # every later dense merge reuses the name order + treedef
        # instead of re-walking the tree with path keys twice per pull.
        named, self._treedef = flatten_with_names(to_numpy(self._params))
        self._flat_names = list(named)
        self._version = 0
        self._steps = 0
        self._grad_step = None
        self._example_serving_input = None
        self._eval_step = None
        self._push_model_to_init()
        # PS restart detection (docs/ps_recovery.md): the client bumps
        # generation_epoch whenever a shard's restart generation
        # changes; seeing it move, this trainer reconciles — drops
        # in-flight pipelined pushes (the shard fences them anyway),
        # invalidates prefetched embeddings, and re-pulls dense state
        # past the local-version fast path.
        self._seen_gen_epoch = getattr(ps_client, "generation_epoch", 0)

    # -- PS interaction -----------------------------------------------------

    def _maybe_reconcile(self):
        """PS restart reconciliation (docs/ps_recovery.md): if the
        client observed a shard generation change since we last looked,
        (1) wait out the in-flight pipelined pushes WITHOUT surfacing
        their rejects — they are stamped with the dead incarnation's
        generation, so the restarted shard fences them; re-pushing them
        would apply gradients computed against abandoned state — (2)
        drop prefetched embedding rows (they predate the restore), and
        (3) re-pull dense state unconditionally (version=-1): the
        restored version is usually BELOW ours, so the normal
        ``request.version < server.version`` fast path would return
        nothing and leave us training on the dead incarnation's params
        forever.  Returns True iff a reconcile ran."""
        epoch = getattr(self._ps, "generation_epoch", 0)
        if epoch == self._seen_gen_epoch:
            return False
        dropped = 0
        while self._push_inflight:
            future = self._push_inflight.popleft()
            try:
                accepted, _ = future.result()
            except Exception as e:  # noqa: BLE001 — dropping anyway
                logger.warning("in-flight push failed during PS "
                               "restart reconcile: %s", e)
                accepted = False
            if not accepted:
                dropped += 1
        self._prefetched.clear()
        initialized, version, dense = self._ps.pull_dense_parameters(-1)
        if not initialized:
            # The shard came back with no restorable checkpoint:
            # re-seed it from the local model mid-run (the race-safe
            # push-to-init path) instead of wedging every pull.
            self._push_model_to_init()
        else:
            if dense:
                self._merge_dense(dense)
            self._version = version
            self._sync_gen_snapshot()
        # Re-read AFTER the pull: generations the forced pull itself
        # noted were answered by that same full response.
        self._seen_gen_epoch = getattr(self._ps, "generation_epoch", 0)
        self.timing.bump("ps_reconcile")
        # Flight-recorder breadcrumb inside the current task's trace:
        # the worker-side half of a PS crash-restart incident
        # (docs/observability.md span taxonomy).
        tracing.event("worker.ps_reconcile", dropped_pushes=dropped,
                      version=self._version,
                      gen_epoch=self._seen_gen_epoch)
        logger.warning(
            "reconciled PS restart: %d in-flight pushes dropped, "
            "prefetch cache invalidated, dense state re-pulled at "
            "version %d", dropped, self._version,
        )
        return True

    def push_staleness(self):
        """Depth of the async push pipeline right now — the bounded-
        staleness telemetry the worker piggybacks on progress RPCs
        (0 for atomic-sync / serialized jobs)."""
        return float(len(self._push_inflight))

    def _recover_embedding_failure(self, err):
        """An embedding pull failed terminally (the client's retry
        policy already rode out what it could).  The dense plane
        carries the diagnosis the embedding plane can't: a shard
        relaunched WITHOUT a restorable checkpoint serves uninitialized
        (its tables are gone, so embedding pulls fail with INTERNAL
        while steps-%-cadence never reaches a dense pull to notice) —
        probe it, re-seed via push-to-init / reconcile as needed, and
        surface the minibatch as rejected so the worker's retry loop
        re-runs it against the recovered shard."""
        if self._maybe_reconcile():
            raise GradientsRejected(
                "PS restarted mid-minibatch; reconciled — retry"
            ) from err
        # Epoch unchanged: probe for an uninitialized relaunch (the
        # probe itself notes generations from the responses).
        initialized, _, _ = self._ps.pull_dense_parameters(-1)
        if not initialized:
            logger.warning(
                "embedding pull failed against an uninitialized PS "
                "(relaunch without checkpoint?); re-seeding: %s", err,
            )
            self._push_model_to_init()
            self._maybe_reconcile()
            raise GradientsRejected(
                "PS re-seeded after relaunch-without-checkpoint — retry"
            ) from err
        if self._maybe_reconcile():
            raise GradientsRejected(
                "PS restarted mid-minibatch; reconciled — retry"
            ) from err
        raise err  # healthy shards: a genuine failure, surface it

    def _sync_gen_snapshot(self):
        """Freeze the per-shard generations the local params were last
        synchronized under.  Every push is stamped with THIS snapshot,
        not whatever the client knows at push-execution time: between a
        pull and a deferred push's execution, a concurrent thread (the
        push executor collecting an earlier fenced reject) can teach
        the client a restarted shard's NEW generation — and a
        then-current stamp would slip a gradient computed against the
        dead incarnation's state past the restart fence."""
        snap = getattr(self._ps, "generation_snapshot", None)
        self._gen_snapshot = snap() if snap is not None else None

    def _push_model_to_init(self):
        """First contact: initialize the PS shards from the local init
        (reference server.go:209-221 push-to-init)."""
        initialized, version, dense = self._ps.pull_dense_parameters(-1)
        if not initialized:
            named, _ = flatten_with_names(to_numpy(self._params))
            self._ps.push_model(
                named, embedding_infos=self._spec.ps_embedding_infos
            )
            initialized, version, dense = self._ps.pull_dense_parameters(-1)
        if dense:
            self._merge_dense(dense)
        self._version = version
        self._sync_gen_snapshot()

    def _pull_dense(self):
        with self.timing.timeit("get_model"):
            initialized, version, dense = self._ps.pull_dense_parameters(
                self._version
            )
            if not initialized:
                # A PS shard restarted without a restorable checkpoint:
                # re-initialize it from the local model (reference
                # test_restart_ps semantics) and continue training.
                logger.warning(
                    "PS uninitialized (restart?); re-pushing model"
                )
                self._push_model_to_init()
                return
            if dense:
                self._merge_dense(dense)
            self._version = version
            self._sync_gen_snapshot()
        # If this very pull discovered a shard restart, the response
        # was already the full restored state (the request still
        # carried the OLD generation, so the server bypassed its
        # version fast path) — the dense half of the reconcile is
        # done.  Only the prefetched rows, which predate the restore,
        # still need dropping (in-flight pushes drained before any
        # cadence pull).
        epoch = getattr(self._ps, "generation_epoch", 0)
        if epoch != self._seen_gen_epoch:
            self._prefetched.clear()
            self._seen_gen_epoch = epoch
            self.timing.bump("ps_reconcile")
            logger.warning(
                "reconciled PS restart at cadence pull: prefetch "
                "cache invalidated, dense state restored at version "
                "%d", self._version,
            )

    def _merge_dense(self, dense):
        """Merge a (possibly partial) dense pull into local params — a
        freshly restored shard can lag the others and return only its
        slice, or nothing at all."""
        leaves = jax.tree_util.tree_leaves(to_numpy(self._params))
        new_leaves = []
        for name, leaf in zip(self._flat_names, leaves):
            arr = dense.get(name)
            if arr is None:
                new_leaves.append(leaf)
            else:
                new_leaves.append(
                    np.asarray(arr).reshape(np.shape(leaf)).astype(
                        np.asarray(leaf).dtype
                    )
                )
        self._params = jax.tree_util.tree_unflatten(
            self._treedef, new_leaves
        )

    # -- async push pipeline ------------------------------------------------

    def _submit_push(self, param_grads, emb_grads, push_info):
        """Queue the push behind the compute; bounded staleness — once
        ``async_push_window`` pushes are in flight, block on the oldest
        (that wait is the pipeline's backpressure).

        Takes the RAW grad pytrees: materializing the async jax arrays,
        flattening, and serializing all happen on the push thread, so
        the step thread never blocks on them (deferred gradient
        materialization — the device step is still running when this
        returns)."""
        while len(self._push_inflight) >= self._push_window:
            self.timing.bump("push_window_stall")
            self._drain_oldest_push()
        version = self._version
        learning_rate = self._learning_rate
        # Stamp with the generations the local params were last SYNCED
        # under (_sync_gen_snapshot) — not submit-time, and certainly
        # not execution-time: either later read could already reflect a
        # restart this minibatch's gradients predate.
        generations = self._gen_snapshot

        def push():
            named_grads, _ = flatten_with_names(to_numpy(param_grads))
            emb_push = {}
            for table, (uniq_ids, n_uniq) in push_info.items():
                emb_push[table] = (
                    np.asarray(emb_grads[table])[:n_uniq], uniq_ids
                )
            # The blocking path leans on the worker's minibatch retry
            # loop to ride out a relaunching PS shard; the async path
            # rides it out here.  A retry-armed client (ps_rpc_policy)
            # carries its own full outage budget per call — wrapping it
            # in _push_retry would MULTIPLY the budgets (6 x 120 s
            # against a permanently dead shard), so the wrapper applies
            # only to the legacy fail-fast client.
            if getattr(self._ps, "retry_policy", None) is not None:
                return self._ps.push_gradients(
                    named_grads, emb_push,
                    version=version, learning_rate=learning_rate,
                    generations=generations,
                )
            return self._push_retry.call(
                self._ps.push_gradients,
                named_grads, emb_push,
                version=version, learning_rate=learning_rate,
                generations=generations,
                description="async gradient push",
            )

        self._push_inflight.append(self._push_pool.submit(push))
        self.timing.bump("push_async_submitted")

    def _drain_oldest_push(self):
        future = self._push_inflight.popleft()
        with self.timing.timeit("push_drain_wait"):
            accepted, _ = future.result()
        if not accepted:
            # Empty the pipeline before surfacing the reject: the
            # worker's retry loop must restart from a known-clean state
            # (pending pushes against the stale version would only be
            # rejected too).  A generation-fenced reject (the shard
            # restarted under us) reconciles instead — the forced full
            # pull there bypasses the fast path the rolled-back server
            # would otherwise starve us through.
            self.drain_pushes()
            if not self._maybe_reconcile():
                self._pull_dense()
            raise GradientsRejected(
                "stale gradients at version %d" % self._version
            )

    def drain_pushes(self):
        """Block until no gradient push is in flight.  Rejects and RPC
        errors are counted and logged, not raised — drain callers
        (reject recovery, eval, close) need the pipeline empty above
        all; the next training push surfaces a persistent failure."""
        while self._push_inflight:
            future = self._push_inflight.popleft()
            try:
                accepted, _ = future.result()
            except Exception as e:  # noqa: BLE001 — see docstring
                logger.warning("async gradient push failed: %s", e)
                accepted = False
            if not accepted:
                self.timing.bump("push_drain_dropped")

    def close(self):
        """Drain the pipeline and stop the background threads; the
        trainer stays usable for eval/export afterwards (pulls are
        synchronous), but not for pipelined training."""
        self.drain_pushes()
        self._prefetched.clear()
        self._push_pool.shutdown(wait=True)
        self._prefetch_pool.shutdown(wait=True)

    # -- embedding plumbing -------------------------------------------------

    def _padded_unique_ids(self, ids):
        """Pad an id array to the static batch size the way _pad_batch
        will (zero rows -> id 0), then unique — prefetch and prepare
        must derive the SAME key for the same logical batch."""
        ids = np.asarray(ids, dtype=np.int64)
        n = ids.shape[0]
        if n < self._batch_size:
            pad = [(0, self._batch_size - n)] + [(0, 0)] * (ids.ndim - 1)
            ids = np.pad(ids, pad)
        return ids, np.unique(ids.reshape(-1))

    def prefetch_embeddings(self, features):
        """Overlap the NEXT batch's embedding pulls with the current
        device step: the worker loop calls this one batch ahead, the
        pulls run on a small background pool, and _prepare_embeddings
        picks the finished rows up by id-set key.

        No-op outside pipelined mode: a prefetched row set predates the
        current batch's push, which is exactly the reordering
        atomic_sync (and an explicit window of 0) promises not to do."""
        if self._push_window == 0:
            return
        if not isinstance(features, dict) or IDS_KEY not in features:
            return
        self._prefetch_active = True
        for table, ids in features[IDS_KEY].items():
            _, uniq = self._padded_unique_ids(ids)
            key = (table, uniq.tobytes())
            if key in self._prefetched:
                continue
            while len(self._prefetched) >= PREFETCH_CACHE_MAX:
                # Drop the oldest entry (insertion order); its pull
                # just becomes an unused background fetch.
                self._prefetched.pop(next(iter(self._prefetched)))
            self._prefetched[key] = self._prefetch_pool.submit(
                self._ps.pull_embedding_vectors, table, uniq,
                self._emb_dims[table],
            )

    def _prepare_embeddings(self, features):
        """Extract ids, pull rows, return (clean_features, emb_inputs,
        push_info)."""
        if not isinstance(features, dict) or IDS_KEY not in features:
            return features, {}, {}
        features = dict(features)
        ids_map = features.pop(IDS_KEY)
        emb_inputs = {}
        push_info = {}
        for table, ids in ids_map.items():
            ids = np.asarray(ids, dtype=np.int64)
            flat = ids.reshape(-1)
            uniq, inverse = np.unique(flat, return_inverse=True)
            n_uniq = uniq.size
            # Pull only the unique rows; pad host-side to the flat id
            # count so the jitted step sees one static shape per batch
            # size without inflating the gRPC payload.
            prefetched = self._prefetched.pop(
                (table, uniq.tobytes()), None
            )
            with self.timing.timeit("pull_embedding"):
                try:
                    if prefetched is not None:
                        rows = prefetched.result()
                        self.timing.bump("prefetch_hit")
                    else:
                        rows = self._ps.pull_embedding_vectors(
                            table, uniq, dim=self._emb_dims[table]
                        )
                        if self._prefetch_active:
                            self.timing.bump("prefetch_miss")
                except grpc.RpcError as err:
                    # Diagnose through the dense plane: an
                    # uninitialized relaunched shard re-seeds, a
                    # restarted one reconciles; either way the
                    # minibatch surfaces as retryable.
                    self._recover_embedding_failure(err)
            padded_rows = np.zeros(
                (flat.size, self._emb_dims[table]), np.float32
            )
            padded_rows[:n_uniq] = rows
            features["idx__" + table] = inverse.reshape(ids.shape).astype(
                np.int32
            )
            emb_inputs[table] = padded_rows
            push_info[table] = (uniq, n_uniq)
        return features, emb_inputs, push_info

    # -- jitted steps -------------------------------------------------------

    def _build_grad_step(self):
        apply_fn = self._spec.apply_fn
        loss_fn = self._spec.loss_fn

        @jax.jit
        def grad_step(params, emb_inputs, features, labels, weights):
            def f(params, emb_inputs):
                feats = dict(features) if isinstance(features, dict) else (
                    features
                )
                if emb_inputs:
                    feats = dict(feats)
                    for table, rows in emb_inputs.items():
                        feats["emb__" + table] = rows
                out = apply_fn(params, feats, True)
                per_example = loss_fn(out, labels).astype(jnp.float32)
                per_example = per_example.reshape(
                    per_example.shape[0], -1
                ).mean(axis=-1)
                return jnp.sum(per_example * weights) / jnp.maximum(
                    jnp.sum(weights), 1.0
                )

            loss, (param_grads, emb_grads) = jax.value_and_grad(
                f, argnums=(0, 1)
            )(params, emb_inputs)
            return loss, param_grads, emb_grads

        return grad_step

    def _build_eval_step(self):
        apply_fn = self._spec.apply_fn

        @jax.jit
        def eval_step(params, emb_inputs, features):
            feats = features
            if emb_inputs:
                feats = dict(features)
                for table, rows in emb_inputs.items():
                    feats["emb__" + table] = rows
            return apply_fn(params, feats, False)

        return eval_step

    # -- Trainer API --------------------------------------------------------

    def train_minibatch(self, features, labels):
        # A PS restart noted since the last step (push response or
        # prefetch-era pull carried a new generation) reconciles BEFORE
        # any state from the dead incarnation is consumed.
        self._maybe_reconcile()
        if self._steps % self._get_model_steps == 0:
            # Pipelined mode: drain in-flight pushes first.  A pull
            # racing a push convoys on the servicer lock behind the
            # apply anyway, but returns the PRE-push state; draining
            # makes every dense pull observe all of this worker's own
            # pushes, so staleness stays bounded by one pull cadence.
            self.drain_pushes()
            self._pull_dense()
        # Pad BEFORE preparing embeddings so id-array shapes are static
        # across partial batches (padding rows look up id 0 with weight 0).
        (features, labels), weights = _pad_batch(
            (features, labels), self._batch_size
        )
        features, emb_inputs, push_info = self._prepare_embeddings(features)
        if self._example_serving_input is None:
            # Serving signature: feature dict with the looked-up
            # emb__<table> rows merged in, exactly what apply_fn sees.
            merged = dict(features) if emb_inputs else features
            for table, rows in (emb_inputs or {}).items():
                merged["emb__" + table] = rows
            self._example_serving_input = jax.tree_util.tree_map(
                lambda a: np.zeros(np.shape(a), np.asarray(a).dtype),
                merged,
            )
        if self._grad_step is None:
            self._grad_step = self._build_grad_step()
        with self.timing.timeit("batch_process"):
            loss, param_grads, emb_grads = self._grad_step(
                self._params, emb_inputs, features, labels, weights
            )
        with self.timing.timeit("report_gradient"):
            if self._push_window > 0:
                # Pipelined: this step's push (including grad
                # materialization + serialization) overlaps the next
                # step's pulls and compute.  A reject surfaces from a
                # later _submit_push/drain after the pipeline empties.
                self._submit_push(param_grads, emb_grads, push_info)
                accepted, version = True, self._version
            else:
                named_grads, _ = flatten_with_names(to_numpy(param_grads))
                emb_push = {}
                for table, (uniq_ids, n_uniq) in push_info.items():
                    rows = np.asarray(emb_grads[table])[:n_uniq]
                    emb_push[table] = (rows, uniq_ids)
                if self._atomic_sync:
                    accepted, version = self._ps.push_gradients_atomic(
                        named_grads, emb_push,
                        version=self._version,
                        learning_rate=self._learning_rate,
                    )
                else:
                    accepted, version = self._ps.push_gradients(
                        named_grads, emb_push,
                        version=self._version,
                        learning_rate=self._learning_rate,
                        # Same frozen stamp as the pipelined path: the
                        # gradients belong to the incarnations the last
                        # sync observed.
                        generations=self._gen_snapshot,
                    )
        if not accepted:
            # Generation-fenced reject (shard restarted, or a 2PC
            # prepare/commit aborted across a mid-transaction shard
            # death): reconcile with a forced full pull; a plain
            # staleness reject re-pulls at the normal fast path.
            if not self._maybe_reconcile():
                self._pull_dense()
            raise GradientsRejected(
                "stale gradients at version %d" % self._version
            )
        # Do NOT adopt the push response's version: _version means "the
        # server version my local params correspond to", and our params
        # still predate the update we just pushed.  Claiming the newer
        # version made the next pull's `request.version < server.version`
        # check pass vacuously, so dense params went permanently stale
        # (caught by test_feature_column_feed_trains_through_ps; the
        # DeepFM tests masked it because embedding pulls aren't
        # version-gated).  _version advances only in _pull_dense.
        self._steps += 1
        # LAZY loss: the push path already materialized the gradients
        # (inline or on the push thread), so syncing on the loss here
        # bought nothing but a host stall.  Callers that need a float
        # pull it explicitly at cadence (worker loss log, benches).
        return loss, version

    # -- fused window API (fused_driver.FusedStepDriver) --------------------

    @property
    def max_window(self):
        """The PS hot path's overlap lives in the async push pipeline
        and the embedding prefetcher, and every step may need a fresh
        pull at the get_model_steps cadence — so the fused driver is a
        window=1 passthrough here (same driver API, per-step loop)."""
        return 1

    def steps_to_boundary(self):
        return None

    def prepare_batch(self, features, labels, count=None):
        """Passthrough: padding happens inside train_minibatch, AFTER
        the embedding-id extraction that must see the raw feature dict
        (IDS_KEY plumbing)."""
        n = jax.tree_util.tree_leaves(features)[0].shape[0]
        return PreparedBatch(
            features, labels, None, n if count is None else count
        )

    def stage_window(self, prepared, to_device=True):
        del to_device  # host-side trainer: nothing to stage
        return StagedWindow(
            len(prepared),
            [b.features for b in prepared],
            [b.labels for b in prepared],
            None,
        )

    def train_window(self, staged):
        """Window=1 passthrough of the fused-driver API: steps run
        sequentially (each may pull/push at its own cadence); losses
        come back as lazy device scalars."""
        losses = []
        version = self._version
        for features, labels in zip(staged.features, staged.labels):
            loss, version = self.train_minibatch(features, labels)
            losses.append(loss)
        return losses[0] if len(losses) == 1 else losses, version

    def evaluate_minibatch(self, features, labels):
        # Flush pending pushes so evaluation reads a PS state that
        # includes everything this worker trained — and reconcile a
        # noted PS restart first, so eval never mixes prefetched rows
        # from a dead incarnation with restored dense state.
        self.drain_pushes()
        self._maybe_reconcile()
        n = jax.tree_util.tree_leaves(features)[0].shape[0]
        (features, labels), _ = _pad_batch(
            (features, labels), self._batch_size
        )
        features, emb_inputs, _ = self._prepare_embeddings(features)
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        outputs = self._eval_step(self._params, emb_inputs, features)
        return np.asarray(outputs)[:n], np.asarray(labels)[:n]

    def predict_minibatch(self, features):
        outputs, _ = self.evaluate_minibatch(
            features, np.zeros((jax.tree_util.tree_leaves(features)[0]
                                .shape[0],), np.int32)
        )
        return outputs

    @property
    def version(self):
        return self._version

    def export_parameters(self):
        named, _ = flatten_with_names(to_numpy(self._params))
        return named

    def serving_bundle(self):
        """Servable over (dense params, features+emb__rows): the server
        looks embedding rows up host-side from the exported tables
        (serving/loader.py lookup_embedding) and feeds them as
        emb__<table> inputs — the PS-path analog of the reference's
        localized SavedModel (model_handler.py:171-236)."""
        if self._example_serving_input is None:
            return None
        apply_fn = self._spec.apply_fn
        return (
            lambda p, x: apply_fn(p, x, False),
            to_numpy(self._params),
            self._example_serving_input,
        )
