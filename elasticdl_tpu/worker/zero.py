"""ZeRO-1 weight-update sharding — flat padded full-coverage partitioner.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (PAPERS.md) made the observation that in data-parallel
training every replica redundantly applies the identical weight update:
sharding the optimizer state (Adam moments cost 2x params) and the
update computation over the replicas recovers ~Nx optimizer memory and
turns the gradient all-reduce into reduce-scatter + all-gather.  This
module is the shape bookkeeping for the repo's implementation
(CollectiveTrainer ``--zero1``), with the elastic twist no paper
covers: re-partitioning live optimizer shards when the world re-forms.

The old ``--zero1`` stub sharded an optimizer leaf only when its dim 0
happened to divide by the data-axis size — most leaves (biases, odd
vocab rows, scalars' neighbors) silently stayed replicated.  Here every
non-scalar leaf is **flattened to 1-D and padded to a multiple of the
shard count**, so every leaf shards regardless of shape:

    leaf [3, 3, 32, 64] -> flat [18432] -> pad [18432] -> 8 x [2304]
    leaf [10]           -> flat [10]    -> pad [16]    -> 8 x [2]

Padding is zeros; with zero gradients and zero moments the padded tail
receives an exactly-zero Adam update, so it never contaminates real
elements, and ``unflatten_state`` is the unpadding view (checkpoint /
inspection / snapshot always see original shapes — checkpoints stay
byte-portable between ``--zero1`` on and off).

Two representations of one optimizer state:

  * **reference** — original leaf shapes, host or device, the form
    checkpoints and snapshots use (``ref_state`` shape skeleton);
  * **flat** — every non-scalar leaf 1-D and padded, dim 0 sharded
    over the data axis (``state_shardings``), the form the train step
    carries.

Elastic re-partition (``repartition``): when the world re-forms N -> M
on a surviving backend, each shard moves device-to-device with
``jax.device_put`` — directly when the padded length stays valid for
M, else via a replicated gather + a tiny jitted re-pad — so Adam
moments survive **bit-exactly** without a host bounce.  The host path
(flatten_state/unflatten_state on numpy) remains the fallback when the
backend did not survive (multi-controller re-init clears XLA backends).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class _LeafSpec:
    """Flat-form geometry of one state leaf: original shape, element
    count, and padded (shard-divisible) length.  ``padded == 0`` marks
    a scalar (rank-0) leaf that stays replicated."""

    __slots__ = ("shape", "size", "padded")

    def __init__(self, shape, num_shards):
        self.shape = tuple(shape)
        if self.shape:
            self.size = int(np.prod(self.shape))
            self.padded = -(-self.size // num_shards) * num_shards
        else:  # scalar: nothing to shard
            self.size = 1
            self.padded = 0


class ZeroPartitioner:
    """Flat padded ZeRO-1 layout for one optimizer-state structure.

    Built per mesh (the shard count is baked into the padding), from
    the *params template* — specs for the optimizer state are derived
    via ``jax.eval_shape(tx.init, params)`` so arbitrary optax state
    structures (moment trees, scalar counts, schedule states) are
    covered without knowing their internals.
    """

    def __init__(self, spec_optimizer, params_template, mesh,
                 data_axis="data"):
        self.mesh = mesh
        self.data_axis = data_axis
        self.num_shards = int(mesh.shape[data_axis])
        self.shard = NamedSharding(mesh, P(data_axis))
        self.replicated = NamedSharding(mesh, P())
        # Original-shape skeletons (ShapeDtypeStructs — no FLOPs, no
        # device memory): the params tree and the optimizer-state tree.
        params_shapes = jax.eval_shape(lambda: params_template)
        self._params_leaves, self._params_treedef = (
            jax.tree_util.tree_flatten(params_shapes)
        )
        state_shapes = jax.eval_shape(spec_optimizer.init, params_shapes)
        self._state_leaves, self._state_treedef = (
            jax.tree_util.tree_flatten(state_shapes)
        )
        self.param_specs = [
            _LeafSpec(leaf.shape, self.num_shards)
            for leaf in self._params_leaves
        ]
        self.state_specs = [
            _LeafSpec(leaf.shape, self.num_shards)
            for leaf in self._state_leaves
        ]
        self._repad_cache = {}
        self._gather_fn = None

    # -- flat <-> reference, traceable (used inside the train step) ---------

    @staticmethod
    def _flatten_leaf(leaf, spec):
        if spec.padded == 0:
            return leaf
        flat = jnp.reshape(leaf, (-1,))
        if spec.padded != spec.size:
            flat = jnp.pad(flat, (0, spec.padded - spec.size))
        return flat

    @staticmethod
    def _unflatten_leaf(leaf, spec):
        if spec.padded == 0:
            return leaf
        return jnp.reshape(leaf[: spec.size], spec.shape)

    def _convert(self, tree, treedef, specs, fn):
        leaves = jax.tree_util.tree_leaves(tree)
        if len(leaves) != len(specs):
            raise ValueError(
                "state has %d leaves but the partitioner was built "
                "for %d (optimizer changed since rebuild?)"
                % (len(leaves), len(specs))
            )
        return jax.tree_util.tree_unflatten(
            treedef, [fn(leaf, spec) for leaf, spec in zip(leaves, specs)]
        )

    def flatten_params(self, tree):
        """Params/grads tree -> flat padded tree (traceable)."""
        return self._convert(tree, self._params_treedef,
                             self.param_specs, self._flatten_leaf)

    def unflatten_params(self, flat):
        """Flat padded params tree -> original shapes (traceable)."""
        return self._convert(flat, self._params_treedef,
                             self.param_specs, self._unflatten_leaf)

    def flatten_state(self, state):
        """Optimizer state (original shapes) -> flat padded form."""
        return self._convert(state, self._state_treedef,
                             self.state_specs, self._flatten_leaf)

    def unflatten_state(self, flat):
        """Flat padded optimizer state -> original shapes (the
        unpadding view used by checkpoint/snapshot/inspection)."""
        return self._convert(flat, self._state_treedef,
                             self.state_specs, self._unflatten_leaf)

    # -- sharding trees ------------------------------------------------------

    def _leaf_sharding(self, leaf, spec):
        if spec.padded == 0:
            return self.replicated  # scalar (step count): expected
        shape = np.shape(leaf) if leaf is not None else (spec.padded,)
        if len(shape) == 1 and shape[0] == spec.padded:
            return self.shard
        # Defensive: a leaf that is not in flat form cannot shard.  The
        # old stub silently replicated here; be loud — replication of a
        # big leaf defeats the memory win the operator asked for.
        logger.warning(
            "zero1: optimizer leaf of shape %s is not in flat form "
            "(expected [%d]); falling back to REPLICATED placement — "
            "per-device memory for this leaf is NOT reduced",
            shape, spec.padded,
        )
        return self.replicated

    def params_shardings(self, sharding):
        """Uniform sharding tree over the params structure."""
        return jax.tree_util.tree_unflatten(
            self._params_treedef, [sharding] * len(self.param_specs)
        )

    def state_shardings(self, flat_state=None):
        """Per-leaf placements for a flat state: dim 0 over the data
        axis for every padded leaf, replicated for scalars."""
        leaves = (
            jax.tree_util.tree_leaves(flat_state)
            if flat_state is not None
            else [None] * len(self.state_specs)
        )
        return jax.tree_util.tree_unflatten(
            self._state_treedef,
            [self._leaf_sharding(leaf, spec)
             for leaf, spec in zip(leaves, self.state_specs)],
        )

    # -- byte accounting (the measured claim) -------------------------------

    def state_bytes(self, flat_state):
        """(replicated_equivalent, per_device_sharded, padding) bytes.

        ``replicated_equivalent``: what every device would hold without
        zero1 (original unpadded leaves).  ``per_device_sharded``: what
        one device holds now (padded/N for sharded leaves, full for
        replicated scalars).  ``padding``: global bytes spent on pad
        elements (the full-coverage overhead)."""
        replicated = sharded = padding = 0
        for leaf, spec in zip(jax.tree_util.tree_leaves(flat_state),
                              self.state_specs):
            itemsize = np.dtype(
                getattr(leaf, "dtype", np.asarray(leaf).dtype)
            ).itemsize
            replicated += spec.size * itemsize
            if spec.padded:
                sharded += spec.padded // self.num_shards * itemsize
                padding += (spec.padded - spec.size) * itemsize
            else:
                sharded += spec.size * itemsize
        return replicated, sharded, padding

    def flat_param_bytes(self):
        """Bytes of one flat padded params/grads tree — the logical
        payload of the per-step reduce-scatter (grads in) and
        all-gather (params out)."""
        total = 0
        for leaf, spec in zip(self._params_leaves, self.param_specs):
            total += (spec.padded or spec.size) * np.dtype(
                leaf.dtype
            ).itemsize
        return total

    # -- host <-> device -----------------------------------------------------

    def place_state(self, host_state):
        """Original-shape host state -> flat sharded device state."""
        flat = self.flatten_state(
            jax.tree_util.tree_map(np.asarray, host_state)
        )
        return jax.tree_util.tree_map(
            jax.device_put, flat, self.state_shardings(flat)
        )

    def gather_to_host(self, flat_state):
        """Flat sharded state -> original-shape HOST state.

        Runs the unpadding view as a jitted program with replicated
        out_shardings: the all-gather happens on-device, so in a
        multi-controller world every process ends up holding the full
        value (``to_numpy`` would otherwise trip over non-addressable
        shards — the PR-6 snapshot/checkpoint bugfix)."""
        if self._gather_fn is None:
            self._gather_fn = jax.jit(
                self.unflatten_state,
                out_shardings=jax.tree_util.tree_unflatten(
                    self._state_treedef,
                    [self.replicated] * len(self.state_specs),
                ),
            )
        from elasticdl_tpu.utils.pytree import to_numpy

        return to_numpy(self._gather_fn(flat_state))

    # -- elastic re-partition ------------------------------------------------

    def _repad_fn(self, size, padded_new):
        """Jitted slice-to-size + pad-to-new-length, sharded out."""
        key = (size, padded_new)
        fn = self._repad_cache.get(key)
        if fn is None:
            fn = jax.jit(
                lambda a: jnp.pad(a[:size], (0, padded_new - size)),
                out_shardings=self.shard,
            )
            self._repad_cache[key] = fn
        return fn

    def repartition(self, old_flat_state, old_partitioner, timing=None):
        """Re-shard a live flat state from ``old_partitioner``'s world
        onto this one, device-to-device, preserving values bit-exactly.

        Fast path: when a leaf's padded length is already divisible by
        the new shard count, ``jax.device_put`` re-shards it directly
        (shard-to-shard copies over the interconnect).  Otherwise the
        leaf is gathered replicated onto the new mesh (still
        device-to-device) and re-padded by a tiny jitted program.
        Raises on a dead backend — the caller falls back to the host
        path."""
        old_leaves = jax.tree_util.tree_leaves(old_flat_state)
        if len(old_leaves) != len(self.state_specs):
            raise ValueError(
                "cannot repartition: state structure changed "
                "(%d leaves vs %d specs)"
                % (len(old_leaves), len(self.state_specs))
            )
        new_leaves = []
        moved = 0
        for leaf, old_spec, new_spec in zip(
            old_leaves, old_partitioner.state_specs, self.state_specs
        ):
            if new_spec.padded == 0:
                new_leaves.append(
                    jax.device_put(leaf, self.replicated)
                )
                continue
            if old_spec.padded == new_spec.padded:
                # Placement-only when the sharding is already the
                # target (same-size re-form): device_put moves nothing,
                # so don't book it as reshard traffic.
                if getattr(leaf, "sharding", None) != self.shard:
                    moved += getattr(leaf, "nbytes", 0)
                new_leaves.append(jax.device_put(leaf, self.shard))
            else:
                moved += getattr(leaf, "nbytes", 0)
                full = jax.device_put(leaf, self.replicated)
                new_leaves.append(
                    self._repad_fn(new_spec.size, new_spec.padded)(full)
                )
        if timing is not None:
            timing.bump("zero1_reshard_bytes", moved)
            timing.bump("zero1_repartitions")
        return jax.tree_util.tree_unflatten(
            self._state_treedef, new_leaves
        )
