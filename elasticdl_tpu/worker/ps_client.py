"""Worker-side parameter-server client.

Parity with elasticdl/python/worker/ps_client.py:37-301: dense params route
to a PS shard by name hash, embedding ids by ``id % N``; pulls/pushes fan
out to all shards as concurrent gRPC futures; duplicate embedding ids are
merged before pushing.

``wire_dtype`` ("bfloat16") compresses every float32 tensor this client
puts on the wire — pushed gradients and pulled embedding rows — to half
the bandwidth; the PS keeps its master copies and accumulation in float32
(the codec upcasts transparently on decode).  ``wire_stats`` counts the
actual serialized bytes per direction so benchmarks and the status page
can report bytes-on-wire without a proxy.
"""

import threading
import uuid

import numpy as np

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.proto.rpc import PServerStub
from elasticdl_tpu.utils import grpc_utils, hashing, tensor_codec


def build_ps_client(ps_addrs, wire_dtype=None,
                    dedicated_push_channels=False):
    """ps_addrs: comma-separated or list of host:port.

    ``dedicated_push_channels`` opens a second connection per shard for
    gradient pushes — required for the pipelined trainer, where a
    background push sharing the pull connection's completion queue
    convoys every foreground pull behind it."""
    if isinstance(ps_addrs, str):
        ps_addrs = [a for a in ps_addrs.split(",") if a]

    def connect():
        channels = []
        for addr in ps_addrs:
            channel = grpc_utils.build_channel(addr)
            grpc_utils.wait_for_channel_ready(channel)
            channels.append(channel)
        return channels

    return PSClient(
        connect(), wire_dtype=wire_dtype,
        push_channels=connect() if dedicated_push_channels else None,
    )


class PSClient:
    def __init__(self, channels, wire_dtype=None, push_channels=None):
        self._stubs = [PServerStub(c) for c in channels]
        # Optional dedicated connections for the (possibly background)
        # gradient push, so bulk push traffic never contends with the
        # latency-sensitive pull path on one HTTP/2 connection.
        self._push_stubs = (
            [PServerStub(c) for c in push_channels]
            if push_channels else self._stubs
        )
        self.num_ps = len(self._stubs)
        if push_channels is not None and len(push_channels) != self.num_ps:
            raise ValueError(
                "push_channels must match channels per shard (%d != %d)"
                % (len(push_channels), self.num_ps)
            )
        if wire_dtype in ("", "float32"):
            wire_dtype = None
        if wire_dtype is not None and wire_dtype not in (
            tensor_codec.WIRE_DTYPES
        ):
            raise ValueError(
                "unsupported wire_dtype %r (have float32, %s)"
                % (wire_dtype, ", ".join(tensor_codec.WIRE_DTYPES))
            )
        self.wire_dtype = wire_dtype
        # table name -> row dim, learned from the embedding infos this
        # client pushes; lets empty pulls keep their (0, dim) shape.
        self._emb_dims = {}
        # Serialized payload bytes per direction.  Bumped from the step
        # thread, the push executor, AND the prefetch pool concurrently,
        # so every += runs under the stats lock (these are the bench's
        # bytes-on-wire artifact — lost updates would skew it).
        self._stats_lock = threading.Lock()
        self.wire_stats = {
            "push_gradient_bytes": 0,
            "pull_dense_bytes": 0,
            "pull_embedding_bytes": 0,
        }

    def _count_bytes(self, key, n):
        with self._stats_lock:
            self.wire_stats[key] += n

    # -- partitioning -------------------------------------------------------

    def partition_dense(self, names):
        buckets = [[] for _ in range(self.num_ps)]
        for name in names:
            buckets[hashing.string_to_id(name, self.num_ps)].append(name)
        return buckets

    # -- model init ---------------------------------------------------------

    def push_model(self, dense, embedding_infos=None, version=0):
        self._remember_dims(embedding_infos)
        buckets = self.partition_dense(dense.keys())
        futures = []
        for shard, names in enumerate(buckets):
            model = tensor_codec.model_to_pb(
                dense={n: dense[n] for n in names},
                infos=embedding_infos or [],
                version=version,
            )
            futures.append(self._stubs[shard].push_model.future(model))
        for f in futures:
            f.result()

    def push_embedding_table_infos(self, infos):
        self._remember_dims(infos)
        model = tensor_codec.model_to_pb(infos=infos)
        futures = [
            stub.push_embedding_table_infos.future(model)
            for stub in self._stubs
        ]
        for f in futures:
            f.result()

    def _remember_dims(self, infos):
        for info in infos or []:
            self._emb_dims[info["name"]] = int(info["dim"])

    # -- dense --------------------------------------------------------------

    def pull_dense_parameters(self, version=-1):
        """Returns (initialized, server_version, {name: array})."""
        req = pb.PullDenseParametersRequest(version=version)
        futures = [
            stub.pull_dense_parameters.future(req) for stub in self._stubs
        ]
        dense = {}
        initialized = True
        server_version = 0
        for f in futures:
            res = f.result()
            self._count_bytes("pull_dense_bytes", res.ByteSize())
            initialized = initialized and res.initialized
            server_version = max(server_version, res.version)
            for name, t in res.dense_parameters.items():
                dense[name] = tensor_codec.pb_to_ndarray(t)
        return initialized, server_version, dense

    # -- embeddings ---------------------------------------------------------

    def pull_embedding_vectors(self, name, ids, dim=None):
        """ids: int64 [n]; returns [n, dim] rows in input order.

        ``dim`` threads the table's row dim through for the empty-ids
        case; omitted, it falls back to the infos this client pushed."""
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size == 0:
            return np.zeros(
                (0, int(dim) if dim else self._emb_dims.get(name, 0)),
                np.float32,
            )
        buckets = hashing.scatter_ids(ids, self.num_ps)
        futures = {}
        for shard, positions in buckets.items():
            req = pb.PullEmbeddingVectorsRequest(
                name=name, wire_dtype=self.wire_dtype or ""
            )
            # .tolist() keeps the proto extend in C instead of a
            # 300k-call python genexpr (profiled hot path).
            req.ids.extend(ids[positions].tolist())
            futures[shard] = (
                positions, self._stubs[shard].pull_embedding_vectors.future(req)
            )
        out = None
        for shard, (positions, future) in futures.items():
            res = future.result()
            self._count_bytes("pull_embedding_bytes", res.ByteSize())
            rows = tensor_codec.pb_to_ndarray(res)
            if out is None:
                out = np.empty((ids.size, rows.shape[1]), np.float32)
            out[positions] = rows
        return out

    # -- gradients ----------------------------------------------------------

    def push_gradients(self, dense_grads, embedding_grads=None,
                       version=0, learning_rate=0.0):
        """dense_grads: {name: array}; embedding_grads:
        {table: (values [n, dim], ids [n])}.  Returns (accepted,
        max_server_version).

        One-shot fan-out: each shard accepts/rejects independently, which
        is fine in async mode (every push stands alone) but not atomic in
        sync mode with num_ps > 1 — use :meth:`push_gradients_atomic` for
        sync jobs so a stale reject on one shard aborts the minibatch on
        every shard."""
        shard_dense, shard_emb = self._shard_gradients(
            dense_grads, embedding_grads
        )
        futures = []
        for shard in range(self.num_ps):
            if not shard_dense[shard] and not shard_emb[shard]:
                continue
            model = tensor_codec.model_to_pb(
                dense=shard_dense[shard],
                embeddings=shard_emb[shard],
                version=version,
                wire_dtype=self.wire_dtype,
            )
            req = pb.PushGradientsRequest(
                gradients=model, learning_rate=learning_rate
            )
            self._count_bytes("push_gradient_bytes", req.ByteSize())
            futures.append(
                self._push_stubs[shard].push_gradients.future(req)
            )
        accepted = True
        max_version = 0
        for f in futures:
            res = f.result()
            accepted = accepted and res.accepted
            max_version = max(max_version, res.version)
        return accepted, max_version

    def _shard_gradients(self, dense_grads, embedding_grads):
        """Route gradients to their owning shards: dense by name hash,
        embedding rows by id mod N (duplicates merged first)."""
        embedding_grads = embedding_grads or {}
        shard_dense = [dict() for _ in range(self.num_ps)]
        for name, g in dense_grads.items():
            shard_dense[hashing.string_to_id(name, self.num_ps)][name] = g
        shard_emb = [dict() for _ in range(self.num_ps)]
        for table, (values, ids) in embedding_grads.items():
            values, ids = tensor_codec.merge_indexed_slices(values, ids)
            owners = np.asarray(ids) % self.num_ps
            for shard in range(self.num_ps):
                sel = owners == shard
                if sel.any():
                    shard_emb[shard][table] = (values[sel], ids[sel])
        return shard_dense, shard_emb

    def push_gradients_atomic(self, dense_grads, embedding_grads=None,
                              version=0, learning_rate=0.0):
        """Cross-shard atomic push (sync mode): prepare on every shard,
        commit only on unanimous accept, abort everywhere otherwise.

        Every shard gets a prepare — including shards that own no
        gradient this minibatch — so sync buffers fill and version
        counters advance in lockstep instead of drifting."""
        txn_id = uuid.uuid4().hex
        shard_dense, shard_emb = self._shard_gradients(
            dense_grads, embedding_grads
        )
        prepare_futures = []
        for shard in range(self.num_ps):
            model = tensor_codec.model_to_pb(
                dense=shard_dense[shard],
                embeddings=shard_emb[shard],
                version=version,
                wire_dtype=self.wire_dtype,
            )
            req = pb.PrepareGradientsRequest(
                txn_id=txn_id, gradients=model,
                learning_rate=learning_rate,
            )
            self._count_bytes("push_gradient_bytes", req.ByteSize())
            prepare_futures.append(
                self._stubs[shard].prepare_gradients.future(req)
            )
        all_accept = True
        max_version = 0
        for f in prepare_futures:
            res = f.result()
            all_accept = all_accept and res.accepted
            max_version = max(max_version, res.version)
        commit_req = pb.CommitGradientsRequest(
            txn_id=txn_id, commit=all_accept
        )
        commit_futures = [
            stub.commit_gradients.future(commit_req)
            for stub in self._stubs
        ]
        committed = True
        for f in commit_futures:
            res = f.result()
            committed = committed and res.accepted
            max_version = max(max_version, res.version)
        # A commit that found no staged txn (TTL-evicted after a long
        # stall) means a shard missed the minibatch: surface it as a
        # failed push so the worker re-pulls and retries — bounded
        # double-apply on the shards that did commit, never a silent
        # half-apply.
        return all_accept and committed, max_version
