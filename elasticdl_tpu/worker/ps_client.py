"""Worker-side parameter-server client.

Parity with elasticdl/python/worker/ps_client.py:37-301: dense params route
to a PS shard by name hash, embedding ids by ``id % N``; pulls/pushes fan
out to all shards as concurrent gRPC futures; duplicate embedding ids are
merged before pushing.

``wire_dtype`` ("bfloat16") compresses every float32 tensor this client
puts on the wire — pushed gradients and pulled embedding rows — to half
the bandwidth; the PS keeps its master copies and accumulation in float32
(the codec upcasts transparently on decode).  ``wire_stats`` counts the
actual serialized bytes per direction so benchmarks and the status page
can report bytes-on-wire without a proxy.

Crash-restart recovery (docs/ps_recovery.md): with ``addrs`` + ``retry``
armed, every pull/push rides a transiently-dead shard through the shared
retry policy (utils/retry.py), rebuilding that shard's channels
generation-counted with age-gated parking — the MasterClient idiom
(docs/master_recovery.md "channel rebuild"): after a shard is SIGKILLed
its old channel can wedge (stale connect backoff, poisoned fd), so each
retry reconnects on a fresh channel; rebuilds are serialized under the
refresh lock, rate-limited, and retired channels are PARKED in an
age-gated deque instead of close()d, because close() cancels other
threads' in-flight RPCs with non-retryable CANCELLED.  Independently,
the client tracks each shard's PS restart GENERATION from every
response: pushes are stamped with the generation the worker last
observed (a dead incarnation's push is rejected server-side, never
mis-applied), and ``generation_epoch`` bumps whenever a known shard's
generation changes so the trainer can reconcile (drop in-flight
pipelined pushes, invalidate prefetched embeddings, re-pull dense state
past the version fast path).
"""

import threading
import time
import uuid
from collections import deque

import grpc
import numpy as np

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.proto.rpc import PServerStub
from elasticdl_tpu.utils import grpc_utils, hashing, tensor_codec
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def build_ps_client(ps_addrs, wire_dtype=None,
                    dedicated_push_channels=False, retry=None,
                    frame_wire="auto"):
    """ps_addrs: comma-separated or list of host:port.

    ``dedicated_push_channels`` opens a second connection per shard for
    gradient pushes — required for the pipelined trainer, where a
    background push sharing the pull connection's completion queue
    convoys every foreground pull behind it.

    ``retry``: a utils.retry.RetryPolicy (e.g. ``ps_rpc_policy()``)
    arming per-shard outage riding with channel rebuild; None keeps the
    historical fail-fast behavior (the worker-level minibatch retry is
    then the only ride-out).

    ``frame_wire``: "auto" (default) negotiates the raw-frame data
    plane per shard from the ``frame_capable`` bit on legacy pull
    responses; "on" forces it (benches/tests); "off" stays on the
    TensorPB encoding everywhere."""
    if isinstance(ps_addrs, str):
        ps_addrs = [a for a in ps_addrs.split(",") if a]

    def connect():
        channels = []
        for addr in ps_addrs:
            channel = grpc_utils.build_channel(addr)
            grpc_utils.wait_for_channel_ready(channel)
            channels.append(channel)
        return channels

    return PSClient(
        connect(), wire_dtype=wire_dtype,
        push_channels=connect() if dedicated_push_channels else None,
        addrs=list(ps_addrs), retry=retry, frame_wire=frame_wire,
    )


class PSClient:
    # A parked channel may only be closed once it is older than any
    # plausible in-flight RPC on it.  The floor covers the default
    # 120 s outage budget; __init__ raises it when the armed policy's
    # deadline is env-tuned longer (ELASTICDL_RPC_DEADLINE_SECS), so a
    # still-riding thread's channel is never close()d under it.
    _RETIRE_AGE_SECS = 150.0
    # Floor between rebuilds of one shard's channels: a wedged channel
    # needs ONE fresh replacement, not one per backoff step of every
    # retrying thread.
    _REBUILD_INTERVAL_SECS = 2.0

    def __init__(self, channels, wire_dtype=None, push_channels=None,
                 addrs=None, retry=None, frame_wire="auto"):
        if push_channels is not None and len(push_channels) != len(channels):
            raise ValueError(
                "push_channels must match channels per shard (%d != %d)"
                % (len(push_channels), len(channels))
            )
        if addrs is not None and len(addrs) != len(channels):
            raise ValueError(
                "addrs must match channels per shard (%d != %d)"
                % (len(addrs), len(channels))
            )
        self._channels = list(channels)
        # Optional dedicated connections for the (possibly background)
        # gradient push, so bulk push traffic never contends with the
        # latency-sensitive pull path on one HTTP/2 connection.  With
        # no dedicated channels, _push_stubs IS _stubs (one list), so a
        # rebuild swaps both views at once.
        self._push_channels = (
            list(push_channels) if push_channels else None
        )
        self._stubs = [PServerStub(c) for c in channels]
        self._push_stubs = (
            [PServerStub(c) for c in push_channels] if push_channels
            else self._stubs
        )
        # Channel-rebuild arming (see module docstring): rebuilds are
        # per-shard generation-counted under the refresh lock; every
        # call site snapshots (stub, gen) under it and runs the RPC
        # outside.
        self._addrs = list(addrs) if addrs else None
        self._refresh_lock = threading.Lock()
        self._conn_gens = [0] * len(self._stubs)
        self._last_rebuilds = [0.0] * len(self._stubs)
        self._retired = deque()   # (channel, retired_at)
        self.num_ps = len(self._stubs)
        # Outage riding: per-shard retries with channel rebuild.  None
        # (direct test construction) = the historical fail-fast client.
        self.retry_policy = retry
        if retry is not None and retry.deadline_secs:
            self._retire_age_secs = max(
                self._RETIRE_AGE_SECS, retry.deadline_secs + 30.0
            )
        else:
            self._retire_age_secs = self._RETIRE_AGE_SECS
        if wire_dtype in ("", "float32"):
            wire_dtype = None
        if wire_dtype is not None and wire_dtype not in (
            tensor_codec.WIRE_DTYPES
        ):
            raise ValueError(
                "unsupported wire_dtype %r (have float32, %s)"
                % (wire_dtype, ", ".join(tensor_codec.WIRE_DTYPES))
            )
        self.wire_dtype = wire_dtype
        # Raw-frame data plane (docs/ps_pipeline.md "Frame wire"):
        # "auto" starts every shard on TensorPB and upgrades it when a
        # legacy pull response advertises frame_capable; "on" forces
        # frames from the first RPC; "off" never leaves TensorPB.  A
        # frame RPC answered UNIMPLEMENTED (rolling upgrade against an
        # older shard) downgrades that shard back to the legacy
        # encoding.  Per-shard plain bools: flips are idempotent and
        # GIL-atomic, so no lock.
        if frame_wire not in ("auto", "on", "off"):
            raise ValueError(
                "frame_wire must be 'auto', 'on' or 'off', got %r"
                % (frame_wire,)
            )
        self._frame_wire = frame_wire
        self._frame_ok = [frame_wire == "on"] * self.num_ps
        # Generation at which a shard refused a frame RPC: its
        # frame_capable advert is ignored until the shard restarts
        # (new generation = possibly a new binary), so a lying advert
        # can't ping-pong upgrade/UNIMPLEMENTED on every RPC.
        self._frame_refused_gen = [None] * self.num_ps
        # table name -> row dim, learned from the embedding infos this
        # client pushes; lets empty pulls keep their (0, dim) shape.
        self._emb_dims = {}
        # Per-shard PS restart generation last observed (0 = unknown),
        # and the epoch counter the trainer watches: it bumps only when
        # a KNOWN generation changes — i.e. the shard restarted under
        # us.  Noted from the step thread, the push executor, and the
        # prefetch pool concurrently, hence the lock.
        self._gen_lock = threading.Lock()
        self._shard_generations = [0] * self.num_ps
        self.generation_epoch = 0
        # Serialized payload bytes per direction AND per wire encoding
        # (frame vs pb), plus the decode-copy bytes the receiving codec
        # pays for each encoding (tensor_codec decode-copy accounting —
        # computed structurally from the very messages this client
        # builds/decodes).  Bumped from the step thread, the push
        # executor, AND the prefetch pool concurrently, so every +=
        # runs under the stats lock (these are the bench's
        # bytes-on-wire artifact — lost updates would skew it).
        self._stats_lock = threading.Lock()
        self.wire_stats = {
            "push_gradient_bytes_pb": 0,
            "push_gradient_bytes_frame": 0,
            "push_decode_copy_bytes_pb": 0,
            "push_decode_copy_bytes_frame": 0,
            "pull_dense_bytes_pb": 0,
            "pull_dense_bytes_frame": 0,
            "pull_dense_decode_copy_bytes_pb": 0,
            "pull_dense_decode_copy_bytes_frame": 0,
            "pull_embedding_bytes": 0,
        }

    def _count_bytes(self, key, n):
        with self._stats_lock:
            self.wire_stats[key] += n

    # -- restart-generation tracking ----------------------------------------

    def known_generation(self, shard):
        with self._gen_lock:
            return self._shard_generations[shard]

    def generation_snapshot(self):
        """All shards' last-observed generations, atomically.  The
        pipelined trainer captures this at SUBMIT time and passes it to
        ``push_gradients(generations=...)``: the push executes later,
        and stamping it with whatever the client knows by THEN would
        let a gradient computed against a dead incarnation's state ride
        in under the new generation once any earlier response taught
        the client about the restart."""
        with self._gen_lock:
            return list(self._shard_generations)

    def _note_generation(self, shard, generation):
        if not generation:
            return  # pre-fencing server or Empty response
        bumped = False
        with self._gen_lock:
            old = self._shard_generations[shard]
            if old != generation:
                self._shard_generations[shard] = generation
                if old:
                    self.generation_epoch += 1
                    bumped = True
        if bumped:
            logger.warning(
                "PS shard %d restarted: generation %d -> %d "
                "(reconcile pending)", shard, old, generation,
            )

    # -- frame-wire negotiation ----------------------------------------------

    def frame_shards(self):
        """Shards currently speaking the raw-frame data plane (for the
        bench/tests and the status surface)."""
        return sum(1 for ok in self._frame_ok if ok)

    def _maybe_upgrade(self, shard, res):
        """A legacy pull response advertising ``frame_capable``
        upgrades this shard's subsequent push/pull traffic to the
        frame RPCs (auto mode only).  An advert from the SAME
        incarnation that already refused a frame RPC is ignored —
        without that memory a server that advertises but doesn't
        implement (version-skewed rollout) would ping-pong every
        request through an UNIMPLEMENTED probe."""
        if res.generation != self._frame_refused_gen[shard]:
            self._frame_refused_gen[shard] = None
        if (self._frame_wire == "auto" and res.frame_capable
                and self._frame_refused_gen[shard] is None
                and not self._frame_ok[shard]):
            self._frame_ok[shard] = True
            logger.info(
                "PS shard %d advertises the frame wire; upgrading "
                "push/pull traffic to frame RPCs", shard,
            )

    def _frame_downgrade(self, shard, err):
        """UNIMPLEMENTED from a frame RPC means the shard predates the
        frame plane (rolling upgrade): drop this shard back to the
        legacy TensorPB encoding and tell the caller to re-issue.
        Anything else — including UNIMPLEMENTED under forced "on"
        mode — is a real failure the caller must surface."""
        code = err.code() if hasattr(err, "code") else None
        if (code != grpc.StatusCode.UNIMPLEMENTED
                or self._frame_wire == "on"):
            return False
        if self._frame_ok[shard]:
            self._frame_ok[shard] = False
            self._frame_refused_gen[shard] = self.known_generation(
                shard)
            logger.warning(
                "PS shard %d does not implement the frame wire; "
                "falling back to TensorPB", shard,
            )
        return True

    # -- outage riding -------------------------------------------------------

    def _refresh_stub(self, shard, method_name, state, push=False):
        """Rebuild this shard's channels and return the fresh stub
        method for the retry loop; None (no rebuild possible) when
        addrs are unknown.  ``state['gen']`` is the rebuild generation
        the caller last saw: if another thread already rebuilt past
        it, no second rebuild — adopt the fresh stub."""
        if self._addrs is None:
            return None
        with self._refresh_lock:
            now = time.monotonic()
            if (
                state["gen"] == self._conn_gens[shard]
                and now - self._last_rebuilds[shard]
                >= self._REBUILD_INTERVAL_SECS
            ):
                self._retired.append((self._channels[shard], now))
                self._channels[shard] = grpc_utils.build_channel(
                    self._addrs[shard]
                )
                self._stubs[shard] = PServerStub(self._channels[shard])
                if self._push_channels is not None:
                    self._retired.append(
                        (self._push_channels[shard], now)
                    )
                    self._push_channels[shard] = grpc_utils.build_channel(
                        self._addrs[shard]
                    )
                    self._push_stubs[shard] = PServerStub(
                        self._push_channels[shard]
                    )
                while self._retired and (
                    now - self._retired[0][1] > self._retire_age_secs
                ):
                    old, _ = self._retired.popleft()
                    try:
                        old.close()
                    except Exception:  # noqa: BLE001 — already broken
                        pass
                self._conn_gens[shard] += 1
                self._last_rebuilds[shard] = now
            state["gen"] = self._conn_gens[shard]
            stub = (
                self._push_stubs[shard] if push else self._stubs[shard]
            )
            return getattr(stub, method_name)

    def _result(self, shard, method_name, rpc_fn, request, future,
                state, push=False):
        """Collect a fan-out future, riding a transiently-dead shard:
        on a retryable failure the call is re-issued synchronously
        through the retry policy, rebuilding this shard's channels
        before each retry.  The parallelism of the fan-out only matters
        on the healthy fast path — an outage is latency-bound on the
        shard's relaunch anyway."""
        try:
            return future.result()
        except Exception as err:  # noqa: BLE001 — classified below
            if self.retry_policy is None or (
                not self.retry_policy.retryable(err)
            ):
                raise
            return self.retry_policy.call(
                rpc_fn, request,
                description="%s (PS shard %d)" % (method_name, shard),
                refresh=lambda: self._refresh_stub(
                    shard, method_name, state, push
                ),
            )

    # -- partitioning -------------------------------------------------------

    def partition_dense(self, names):
        buckets = [[] for _ in range(self.num_ps)]
        for name in names:
            buckets[hashing.string_to_id(name, self.num_ps)].append(name)
        return buckets

    # -- model init ---------------------------------------------------------

    def push_model(self, dense, embedding_infos=None, version=0):
        self._remember_dims(embedding_infos)
        buckets = self.partition_dense(dense.keys())
        pending = []
        for shard, names in enumerate(buckets):
            model = tensor_codec.model_to_pb(
                dense={n: dense[n] for n in names},
                infos=embedding_infos or [],
                version=version,
            )
            with self._refresh_lock:
                stub = self._stubs[shard]
                state = {"gen": self._conn_gens[shard]}
            pending.append((shard, model, stub.push_model,
                            stub.push_model.future(model), state))
        for shard, req, rpc_fn, future, state in pending:
            self._result(shard, "push_model", rpc_fn, req, future, state)

    def push_embedding_table_infos(self, infos):
        self._remember_dims(infos)
        model = tensor_codec.model_to_pb(infos=infos)
        pending = []
        for shard in range(self.num_ps):
            with self._refresh_lock:
                stub = self._stubs[shard]
                state = {"gen": self._conn_gens[shard]}
            pending.append((
                shard, stub.push_embedding_table_infos,
                stub.push_embedding_table_infos.future(model), state,
            ))
        for shard, rpc_fn, future, state in pending:
            self._result(shard, "push_embedding_table_infos", rpc_fn,
                         model, future, state)

    def _remember_dims(self, infos):
        for info in infos or []:
            self._emb_dims[info["name"]] = int(info["dim"])

    # -- dense --------------------------------------------------------------

    def pull_dense_parameters(self, version=-1):
        """Returns (initialized, server_version, {name: array}).

        Each shard's request carries the generation this client last
        observed for it: a restarted shard answers with the full dense
        state even when its restored version is BELOW ours (the fast
        path comparison points the wrong way after a rollback).

        A frame-upgraded shard is pulled over the raw-frame RPC (one
        blob, zero-copy decode); everyone else rides the legacy
        TensorPB response, whose ``frame_capable`` bit is how "auto"
        mode learns to upgrade the shard for NEXT time."""
        pending = []
        for shard in range(self.num_ps):
            req = pb.PullDenseParametersRequest(
                version=version,
                generation=self.known_generation(shard),
            )
            framed = self._frame_ok[shard]
            with self._refresh_lock:
                stub = self._stubs[shard]
                state = {"gen": self._conn_gens[shard]}
            if framed:
                rpc_fn = stub.pull_dense_parameters_frame
                future = stub.pull_dense_parameters_frame.future(req)
            else:
                rpc_fn = stub.pull_dense_parameters
                future = stub.pull_dense_parameters.future(req)
            pending.append((shard, framed, req, rpc_fn, future, state))
        dense = {}
        initialized = True
        server_version = 0
        for shard, framed, req, rpc_fn, future, state in pending:
            if framed:
                try:
                    blob = self._result(
                        shard, "pull_dense_parameters_frame", rpc_fn,
                        req, future, state,
                    )
                except Exception as err:  # noqa: BLE001 — classified
                    if not self._frame_downgrade(shard, err):
                        raise
                    # Rolling downgrade: re-issue the SAME request on
                    # the legacy RPC with a fresh stub snapshot.
                    with self._refresh_lock:
                        stub = self._stubs[shard]
                        state = {"gen": self._conn_gens[shard]}
                    rpc_fn = stub.pull_dense_parameters
                    future = stub.pull_dense_parameters.future(req)
                else:
                    header = tensor_codec.peek_frame_header(blob)
                    (shard_init, shard_version, generation,
                     shard_dense) = tensor_codec.decode_params_frame(
                        blob)
                    self._note_generation(shard, generation)
                    self._count_bytes("pull_dense_bytes_frame",
                                      len(blob))
                    self._count_bytes(
                        "pull_dense_decode_copy_bytes_frame",
                        tensor_codec.frame_decode_copy_bytes(header),
                    )
                    initialized = initialized and shard_init
                    server_version = max(server_version, shard_version)
                    dense.update(shard_dense)
                    continue
            res = self._result(shard, "pull_dense_parameters", rpc_fn,
                               req, future, state)
            self._note_generation(shard, res.generation)
            self._maybe_upgrade(shard, res)
            self._count_bytes("pull_dense_bytes_pb", res.ByteSize())
            self._count_bytes(
                "pull_dense_decode_copy_bytes_pb",
                sum(tensor_codec.pb_decode_copy_bytes(t)
                    for t in res.dense_parameters.values()),
            )
            initialized = initialized and res.initialized
            server_version = max(server_version, res.version)
            for name, t in res.dense_parameters.items():
                dense[name] = tensor_codec.pb_to_ndarray(t)
        return initialized, server_version, dense

    # -- embeddings ---------------------------------------------------------

    def pull_embedding_vectors(self, name, ids, dim=None,
                               read_only=False):
        """ids: int64 [n]; returns [n, dim] rows in input order.

        ``dim`` threads the table's row dim through for the empty-ids
        case; omitted, it falls back to the infos this client pushed.

        ``read_only`` is the serving-tier lookup mode: absent ids come
        back as zero rows and are never lazily initialized on the PS
        (docs/serving.md fleet section), and the response's generation
        stamp keeps this client's restart-generation view current even
        when it never touches the dense plane."""
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size == 0:
            return np.zeros(
                (0, int(dim) if dim else self._emb_dims.get(name, 0)),
                np.float32,
            )
        buckets = hashing.scatter_ids(ids, self.num_ps)
        pending = {}
        for shard, positions in buckets.items():
            req = pb.PullEmbeddingVectorsRequest(
                name=name, wire_dtype=self.wire_dtype or "",
                read_only=read_only,
            )
            # .tolist() keeps the proto extend in C instead of a
            # 300k-call python genexpr (profiled hot path).
            req.ids.extend(ids[positions].tolist())
            with self._refresh_lock:
                stub = self._stubs[shard]
                state = {"gen": self._conn_gens[shard]}
            pending[shard] = (
                positions, req, stub.pull_embedding_vectors,
                stub.pull_embedding_vectors.future(req), state,
            )
        out = None
        for shard, (positions, req, rpc_fn, future,
                    state) in pending.items():
            res = self._result(shard, "pull_embedding_vectors", rpc_fn,
                               req, future, state)
            # Lookup responses carry the shard's restart generation
            # (TensorPB.generation, 0 = pre-stamping server): an
            # embedding-only client — the serving hot-row cache — must
            # learn about a crash-restore rollback from the lookups
            # themselves, not only from dense pulls it never issues.
            self._note_generation(shard, res.generation)
            self._count_bytes("pull_embedding_bytes", res.ByteSize())
            rows = tensor_codec.pb_to_ndarray(res)
            if out is None:
                out = np.empty((ids.size, rows.shape[1]), np.float32)
            out[positions] = rows
        return out

    # -- gradients ----------------------------------------------------------

    def push_gradients(self, dense_grads, embedding_grads=None,
                       version=0, learning_rate=0.0, generations=None):
        """dense_grads: {name: array}; embedding_grads:
        {table: (values [n, dim], ids [n])}.  Returns (accepted,
        max_server_version).

        One-shot fan-out: each shard accepts/rejects independently, which
        is fine in async mode (every push stands alone) but not atomic in
        sync mode with num_ps > 1 — use :meth:`push_gradients_atomic` for
        sync jobs so a stale reject on one shard aborts the minibatch on
        every shard.

        Each shard's request is stamped with the PS generation this
        client last observed for it (or the caller's frozen
        ``generations`` snapshot — see :meth:`generation_snapshot`: a
        DEFERRED push must be stamped with the generation its gradients
        were computed under, not whatever is current when it finally
        executes); a shard that restarted since then rejects the push
        outright (restart fencing) and the reject response's new
        generation bumps ``generation_epoch`` so the trainer
        reconciles."""
        shard_dense, shard_emb = self._shard_gradients(
            dense_grads, embedding_grads
        )
        pending = []
        for shard in range(self.num_ps):
            if not shard_dense[shard] and not shard_emb[shard]:
                continue
            generation = (
                generations[shard] if generations is not None
                else self.known_generation(shard)
            )
            framed = self._frame_ok[shard]
            if framed:
                # One frame blob IS the gRPC message (RawFrame identity
                # codec): generation and lr ride in the frame header's
                # meta so the servicer fences before decoding.
                blob = tensor_codec.encode_grads_frame(
                    dense=shard_dense[shard],
                    embeddings=shard_emb[shard],
                    version=version,
                    learning_rate=learning_rate,
                    generation=generation,
                    wire_dtype=self.wire_dtype,
                )
                self._count_bytes("push_gradient_bytes_frame",
                                  len(blob))
                self._count_bytes(
                    "push_decode_copy_bytes_frame",
                    tensor_codec.frame_decode_copy_bytes(
                        tensor_codec.peek_frame_header(blob)),
                )
            else:
                model = tensor_codec.model_to_pb(
                    dense=shard_dense[shard],
                    embeddings=shard_emb[shard],
                    version=version,
                    wire_dtype=self.wire_dtype,
                )
                req = pb.PushGradientsRequest(
                    gradients=model, learning_rate=learning_rate,
                    generation=generation,
                )
                self._count_bytes("push_gradient_bytes_pb",
                                  req.ByteSize())
                self._count_bytes(
                    "push_decode_copy_bytes_pb",
                    tensor_codec.model_pb_decode_copy_bytes(model),
                )
            with self._refresh_lock:
                stub = self._push_stubs[shard]
                state = {"gen": self._conn_gens[shard]}
            if framed:
                req = blob
                rpc_fn = stub.push_gradients_frame
                future = stub.push_gradients_frame.future(blob)
            else:
                rpc_fn = stub.push_gradients
                future = stub.push_gradients.future(req)
            pending.append((shard, framed, generation, req, rpc_fn,
                            future, state))
        accepted = True
        max_version = 0
        for (shard, framed, generation, req, rpc_fn, future,
             state) in pending:
            if framed:
                try:
                    res = self._result(shard, "push_gradients_frame",
                                       rpc_fn, req, future, state,
                                       push=True)
                except Exception as err:  # noqa: BLE001 — classified
                    if not self._frame_downgrade(shard, err):
                        raise
                    # Rebuild the legacy request from the still-held
                    # shard buckets, stamped with the SAME generation
                    # the frame carried — re-stamping with a fresher
                    # one would unfence a pre-restart gradient.
                    model = tensor_codec.model_to_pb(
                        dense=shard_dense[shard],
                        embeddings=shard_emb[shard],
                        version=version,
                        wire_dtype=self.wire_dtype,
                    )
                    legacy = pb.PushGradientsRequest(
                        gradients=model,
                        learning_rate=learning_rate,
                        generation=generation,
                    )
                    self._count_bytes("push_gradient_bytes_pb",
                                      legacy.ByteSize())
                    self._count_bytes(
                        "push_decode_copy_bytes_pb",
                        tensor_codec.model_pb_decode_copy_bytes(model),
                    )
                    with self._refresh_lock:
                        stub = self._push_stubs[shard]
                        state = {"gen": self._conn_gens[shard]}
                    res = self._result(
                        shard, "push_gradients", stub.push_gradients,
                        legacy, stub.push_gradients.future(legacy),
                        state, push=True,
                    )
            else:
                res = self._result(shard, "push_gradients", rpc_fn,
                                   req, future, state, push=True)
            self._note_generation(shard, res.generation)
            accepted = accepted and res.accepted
            max_version = max(max_version, res.version)
        return accepted, max_version

    def _shard_gradients(self, dense_grads, embedding_grads):
        """Route gradients to their owning shards: dense by name hash,
        embedding rows by id mod N (duplicates merged first)."""
        embedding_grads = embedding_grads or {}
        shard_dense = [dict() for _ in range(self.num_ps)]
        for name, g in dense_grads.items():
            shard_dense[hashing.string_to_id(name, self.num_ps)][name] = g
        shard_emb = [dict() for _ in range(self.num_ps)]
        for table, (values, ids) in embedding_grads.items():
            values, ids = tensor_codec.merge_indexed_slices(values, ids)
            owners = np.asarray(ids) % self.num_ps
            for shard in range(self.num_ps):
                sel = owners == shard
                if sel.any():
                    shard_emb[shard][table] = (values[sel], ids[sel])
        return shard_dense, shard_emb

    def push_gradients_atomic(self, dense_grads, embedding_grads=None,
                              version=0, learning_rate=0.0):
        """Cross-shard atomic push (sync mode): prepare on every shard,
        commit only on unanimous accept, abort everywhere otherwise.

        Every shard gets a prepare — including shards that own no
        gradient this minibatch — so sync buffers fill and version
        counters advance in lockstep instead of drifting.  Prepares are
        generation-stamped like plain pushes: a shard that died and
        relaunched mid-protocol rejects its prepare, the transaction
        aborts on EVERY shard, and nothing is half-applied."""
        txn_id = uuid.uuid4().hex
        shard_dense, shard_emb = self._shard_gradients(
            dense_grads, embedding_grads
        )
        pending = []
        for shard in range(self.num_ps):
            model = tensor_codec.model_to_pb(
                dense=shard_dense[shard],
                embeddings=shard_emb[shard],
                version=version,
                wire_dtype=self.wire_dtype,
            )
            req = pb.PrepareGradientsRequest(
                txn_id=txn_id, gradients=model,
                learning_rate=learning_rate,
                generation=self.known_generation(shard),
            )
            # 2PC stays on the TensorPB wire (docs/ps_pipeline.md
            # "Frame wire" fallback matrix).
            self._count_bytes("push_gradient_bytes_pb", req.ByteSize())
            with self._refresh_lock:
                stub = self._stubs[shard]
                state = {"gen": self._conn_gens[shard]}
            pending.append((shard, req, stub.prepare_gradients,
                            stub.prepare_gradients.future(req), state))
        all_accept = True
        max_version = 0
        for shard, req, rpc_fn, future, state in pending:
            res = self._result(shard, "prepare_gradients", rpc_fn, req,
                               future, state)
            self._note_generation(shard, res.generation)
            all_accept = all_accept and res.accepted
            max_version = max(max_version, res.version)
        commit_req = pb.CommitGradientsRequest(
            txn_id=txn_id, commit=all_accept
        )
        pending = []
        for shard in range(self.num_ps):
            with self._refresh_lock:
                stub = self._stubs[shard]
                state = {"gen": self._conn_gens[shard]}
            pending.append((shard, stub.commit_gradients,
                            stub.commit_gradients.future(commit_req),
                            state))
        committed = True
        for shard, rpc_fn, future, state in pending:
            res = self._result(shard, "commit_gradients", rpc_fn,
                               commit_req, future, state)
            self._note_generation(shard, res.generation)
            committed = committed and res.accepted
            max_version = max(max_version, res.version)
        # A commit that found no staged txn (TTL-evicted after a long
        # stall, or the shard died and relaunched between phases) means
        # a shard missed the minibatch: surface it as a failed push so
        # the worker re-pulls and retries — bounded double-apply on the
        # shards that did commit, never a silent half-apply.
        return all_accept and committed, max_version
