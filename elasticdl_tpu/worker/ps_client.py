"""Worker-side parameter-server client.

Parity with elasticdl/python/worker/ps_client.py:37-301: dense params route
to a PS shard by name hash, embedding ids by ``id % N``; pulls/pushes fan
out to all shards as concurrent gRPC futures; duplicate embedding ids are
merged before pushing.
"""

import uuid

import numpy as np

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.proto.rpc import PServerStub
from elasticdl_tpu.utils import grpc_utils, hashing, tensor_codec


def build_ps_client(ps_addrs):
    """ps_addrs: comma-separated or list of host:port."""
    if isinstance(ps_addrs, str):
        ps_addrs = [a for a in ps_addrs.split(",") if a]
    channels = []
    for addr in ps_addrs:
        channel = grpc_utils.build_channel(addr)
        grpc_utils.wait_for_channel_ready(channel)
        channels.append(channel)
    return PSClient(channels)


class PSClient:
    def __init__(self, channels):
        self._stubs = [PServerStub(c) for c in channels]
        self.num_ps = len(self._stubs)

    # -- partitioning -------------------------------------------------------

    def partition_dense(self, names):
        buckets = [[] for _ in range(self.num_ps)]
        for name in names:
            buckets[hashing.string_to_id(name, self.num_ps)].append(name)
        return buckets

    # -- model init ---------------------------------------------------------

    def push_model(self, dense, embedding_infos=None, version=0):
        buckets = self.partition_dense(dense.keys())
        futures = []
        for shard, names in enumerate(buckets):
            model = tensor_codec.model_to_pb(
                dense={n: dense[n] for n in names},
                infos=embedding_infos or [],
                version=version,
            )
            futures.append(self._stubs[shard].push_model.future(model))
        for f in futures:
            f.result()

    def push_embedding_table_infos(self, infos):
        model = tensor_codec.model_to_pb(infos=infos)
        futures = [
            stub.push_embedding_table_infos.future(model)
            for stub in self._stubs
        ]
        for f in futures:
            f.result()

    # -- dense --------------------------------------------------------------

    def pull_dense_parameters(self, version=-1):
        """Returns (initialized, server_version, {name: array})."""
        req = pb.PullDenseParametersRequest(version=version)
        futures = [
            stub.pull_dense_parameters.future(req) for stub in self._stubs
        ]
        dense = {}
        initialized = True
        server_version = 0
        for f in futures:
            res = f.result()
            initialized = initialized and res.initialized
            server_version = max(server_version, res.version)
            for name, t in res.dense_parameters.items():
                dense[name] = tensor_codec.pb_to_ndarray(t)
        return initialized, server_version, dense

    # -- embeddings ---------------------------------------------------------

    def pull_embedding_vectors(self, name, ids):
        """ids: int64 [n]; returns [n, dim] rows in input order."""
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size == 0:
            return np.zeros((0, 0), np.float32)
        buckets = hashing.scatter_ids(ids, self.num_ps)
        futures = {}
        for shard, positions in buckets.items():
            req = pb.PullEmbeddingVectorsRequest(name=name)
            # .tolist() keeps the proto extend in C instead of a
            # 300k-call python genexpr (profiled hot path).
            req.ids.extend(ids[positions].tolist())
            futures[shard] = (
                positions, self._stubs[shard].pull_embedding_vectors.future(req)
            )
        out = None
        for shard, (positions, future) in futures.items():
            rows = tensor_codec.pb_to_ndarray(future.result())
            if out is None:
                out = np.empty((ids.size, rows.shape[1]), np.float32)
            out[positions] = rows
        return out

    # -- gradients ----------------------------------------------------------

    def push_gradients(self, dense_grads, embedding_grads=None,
                       version=0, learning_rate=0.0):
        """dense_grads: {name: array}; embedding_grads:
        {table: (values [n, dim], ids [n])}.  Returns (accepted,
        max_server_version).

        One-shot fan-out: each shard accepts/rejects independently, which
        is fine in async mode (every push stands alone) but not atomic in
        sync mode with num_ps > 1 — use :meth:`push_gradients_atomic` for
        sync jobs so a stale reject on one shard aborts the minibatch on
        every shard."""
        shard_dense, shard_emb = self._shard_gradients(
            dense_grads, embedding_grads
        )
        futures = []
        for shard in range(self.num_ps):
            if not shard_dense[shard] and not shard_emb[shard]:
                continue
            model = tensor_codec.model_to_pb(
                dense=shard_dense[shard],
                embeddings=shard_emb[shard],
                version=version,
            )
            req = pb.PushGradientsRequest(
                gradients=model, learning_rate=learning_rate
            )
            futures.append(self._stubs[shard].push_gradients.future(req))
        accepted = True
        max_version = 0
        for f in futures:
            res = f.result()
            accepted = accepted and res.accepted
            max_version = max(max_version, res.version)
        return accepted, max_version

    def _shard_gradients(self, dense_grads, embedding_grads):
        """Route gradients to their owning shards: dense by name hash,
        embedding rows by id mod N (duplicates merged first)."""
        embedding_grads = embedding_grads or {}
        shard_dense = [dict() for _ in range(self.num_ps)]
        for name, g in dense_grads.items():
            shard_dense[hashing.string_to_id(name, self.num_ps)][name] = g
        shard_emb = [dict() for _ in range(self.num_ps)]
        for table, (values, ids) in embedding_grads.items():
            values, ids = tensor_codec.merge_indexed_slices(values, ids)
            owners = np.asarray(ids) % self.num_ps
            for shard in range(self.num_ps):
                sel = owners == shard
                if sel.any():
                    shard_emb[shard][table] = (values[sel], ids[sel])
        return shard_dense, shard_emb

    def push_gradients_atomic(self, dense_grads, embedding_grads=None,
                              version=0, learning_rate=0.0):
        """Cross-shard atomic push (sync mode): prepare on every shard,
        commit only on unanimous accept, abort everywhere otherwise.

        Every shard gets a prepare — including shards that own no
        gradient this minibatch — so sync buffers fill and version
        counters advance in lockstep instead of drifting."""
        txn_id = uuid.uuid4().hex
        shard_dense, shard_emb = self._shard_gradients(
            dense_grads, embedding_grads
        )
        prepare_futures = []
        for shard in range(self.num_ps):
            model = tensor_codec.model_to_pb(
                dense=shard_dense[shard],
                embeddings=shard_emb[shard],
                version=version,
            )
            req = pb.PrepareGradientsRequest(
                txn_id=txn_id, gradients=model,
                learning_rate=learning_rate,
            )
            prepare_futures.append(
                self._stubs[shard].prepare_gradients.future(req)
            )
        all_accept = True
        max_version = 0
        for f in prepare_futures:
            res = f.result()
            all_accept = all_accept and res.accepted
            max_version = max(max_version, res.version)
        commit_req = pb.CommitGradientsRequest(
            txn_id=txn_id, commit=all_accept
        )
        commit_futures = [
            stub.commit_gradients.future(commit_req)
            for stub in self._stubs
        ]
        committed = True
        for f in commit_futures:
            res = f.result()
            committed = committed and res.accepted
            max_version = max(max_version, res.version)
        # A commit that found no staged txn (TTL-evicted after a long
        # stall) means a shard missed the minibatch: surface it as a
        # failed push so the worker re-pulls and retries — bounded
        # double-apply on the shards that did commit, never a silent
        # half-apply.
        return all_accept and committed, max_version
