"""Collective data-parallel trainer — the TPU-native AllReduce path.

Replaces the reference's Horovod/Gloo AllReduce trainer
(elasticdl/python/worker/allreduce_trainer.py:37-146) with a jitted train
step over a ``jax.sharding.Mesh``: the batch is sharded on the ``data`` axis,
parameters are replicated, and XLA inserts the gradient all-reduce over ICI.
Fixed-global-batch elasticity (reference
elasticai_api/pytorch/optimizer.py:136-169) becomes a ``lax.scan`` gradient
accumulation over microbatches, re-jitted when the accumulation count
changes with the world size.  Rebuilding for a new mesh = re-sharding params
and re-jitting — the compile cache keyed by (mesh shape, accum steps).
"""


import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.pytree import flatten_with_names, to_numpy
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.trainer import Trainer

logger = get_logger(__name__)


def _masked_mean(per_example, weights):
    per_example = per_example.reshape(per_example.shape[0], -1).mean(axis=-1)
    return jnp.sum(per_example * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def _pad_batch(tree, batch_size):
    """Pad every leaf to batch_size rows; returns (padded, weights)."""
    leaves = jax.tree_util.tree_leaves(tree)
    n = leaves[0].shape[0]
    if n > batch_size:
        raise ValueError(
            "minibatch has %d records > trainer's global batch %d"
            % (n, batch_size)
        )
    weights = np.zeros((batch_size,), dtype=np.float32)
    weights[:n] = 1.0
    if n == batch_size:
        return tree, weights

    def pad(a):
        a = np.asarray(a)
        pad_width = [(0, batch_size - n)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, pad_width)

    return jax.tree_util.tree_map(pad, tree), weights


class CollectiveTrainer(Trainer):
    def __init__(
        self,
        spec,
        batch_size,
        mesh=None,
        data_axis="data",
        accum_steps=1,
        rng_seed=0,
        master_client=None,
        report_version_steps=0,
        checkpoint_saver=None,
        checkpoint_steps=0,
        use_bf16_compute=False,
        zero1=False,
    ):
        self._spec = spec
        self._batch_size = batch_size
        self._data_axis = data_axis
        self._accum_steps = accum_steps
        self._mc = master_client
        self._report_version_steps = report_version_steps
        self._checkpoint_saver = checkpoint_saver
        self._checkpoint_steps = checkpoint_steps
        self._use_bf16_compute = use_bf16_compute
        # ZeRO-1: shard optimizer state over the data axis instead of
        # replicating it — Adam moments cost 2x params, so an 8-way dp
        # mesh drops per-device optimizer memory ~8x.  XLA places the
        # update math on each leaf's shard owner and re-gathers the
        # params (GSPMD annotation-driven; no reference counterpart —
        # deliberate beyond-reference design, SURVEY §2.12).
        self._zero1 = zero1
        self.timing = Timing(logger=logger)
        self._version = 0
        self._ckpt_executor = None
        self._ckpt_future = None
        self._example_features = None

        params = spec.init_fn(jax.random.PRNGKey(rng_seed))
        self._opt_state = spec.optimizer.init(params)
        self._params = params
        self._mesh = None
        self.rebuild(mesh)

    # -- mesh / jit management ---------------------------------------------

    def snapshot_to_host(self):
        """Pull params + optimizer state to host numpy, in place.

        Called by the elastic controller BEFORE re-forming a
        master-coordinated world: the re-init clears XLA backends, which
        invalidates every device array of the old epoch.  Replicated
        leaves always survive (each process holds a full copy).  A
        ZeRO-1-sharded optimizer leaf is only partially addressable —
        when a peer died, its shard died with it, so the leaf cannot be
        reassembled: optimizer state is re-initialized from the (still
        complete) params, and training resumes with fresh moments (the
        same information loss the reference accepts when a Horovod
        restart reloads the last checkpoint without optimizer slots)."""
        try:
            self._params = to_numpy(self._params)
        except Exception as e:
            raise RuntimeError(
                "parameters are not locally addressable; cannot "
                "survive a world change without a checkpoint restore"
            ) from e
        try:
            self._opt_state = to_numpy(self._opt_state)
        except Exception:  # noqa: BLE001 — lost ZeRO-1 shards
            logger.warning(
                "optimizer state not locally addressable (ZeRO-1 "
                "shards lost with a dead peer); re-initializing "
                "optimizer moments from params"
            )
            self._opt_state = self._spec.optimizer.init(self._params)

    def rebuild(self, mesh):
        """(Re)shard state and (re)compile steps for a (new) mesh.

        This is the elastic-resize path: called at init and whenever the
        rendezvous epoch changes the device world.
        """
        self._mesh = mesh
        if mesh is not None:
            replicated = NamedSharding(mesh, P())
            self._batch_sharding = NamedSharding(mesh, P(self._data_axis))
            self._params = jax.device_put(to_numpy(self._params), replicated)
            self._opt_state = self._place_opt_state(
                to_numpy(self._opt_state)
            )
            self._replicated = replicated
        else:
            self._batch_sharding = None
            self._replicated = None
        self._train_step = self._build_train_step()
        self._eval_step = self._build_eval_step()
        self._local_eval_step = None  # rebuilt lazily: the old one may
        # belong to a cleared backend (world change)

    def _opt_leaf_sharding(self, leaf):
        """ZeRO-1 placement for one optimizer-state leaf: shard dim 0
        over the data axis when divisible, replicate otherwise (scalars,
        odd shapes)."""
        n = self._mesh.shape[self._data_axis]
        shape = np.shape(leaf)
        if self._zero1 and shape and shape[0] % n == 0:
            return NamedSharding(self._mesh, P(self._data_axis))
        return NamedSharding(self._mesh, P())

    def _place_opt_state(self, opt_state):
        if self._mesh is None:
            return opt_state
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(
                leaf, self._opt_leaf_sharding(leaf)
            ),
            opt_state,
        )

    def _opt_out_shardings(self):
        """Sharding tree matching the opt state for jit out_shardings."""
        return jax.tree_util.tree_map(
            lambda leaf: self._opt_leaf_sharding(leaf), self._opt_state
        )

    @property
    def global_device_count(self):
        return self._mesh.size if self._mesh is not None else 1

    @property
    def process_count(self):
        """Number of processes the mesh spans (1 = single-controller)."""
        if self._mesh is None:
            return 1
        return len({d.process_index for d in self._mesh.devices.flat})

    def _globalize(self, tree, sharding):
        """Assemble per-process local batches into global arrays.

        Multi-controller SPMD: every process holds ITS share of the
        global batch (its own task stream's records); the global array
        is the concatenation over processes along the data axis.  The
        single-process path hands numpy straight to jit (placement via
        in_shardings) — identical math, no assembly step."""
        if self.process_count == 1:
            return tree
        return jax.tree_util.tree_map(
            lambda a: jax.make_array_from_process_local_data(
                sharding, np.asarray(a)
            ),
            tree,
        )


    def set_accum_steps(self, accum_steps):
        if accum_steps != self._accum_steps:
            self._accum_steps = accum_steps
            self._train_step = self._build_train_step()

    def _loss_and_grads(self, params, features, labels, weights):
        apply_fn = self._spec.apply_fn
        loss_fn = self._spec.loss_fn

        def f(p):
            x = features
            if self._use_bf16_compute:
                # Cast params AND activations: flax promotes mixed
                # bf16-param/f32-input matmuls back to f32, which would
                # silently keep the MXU off the bf16 path.
                to_bf16 = lambda a: (
                    a.astype(jnp.bfloat16)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a
                )
                p = jax.tree_util.tree_map(to_bf16, p)
                x = jax.tree_util.tree_map(to_bf16, x)
            out = apply_fn(p, x, True)
            per_example = loss_fn(out, labels).astype(jnp.float32)
            return _masked_mean(per_example, weights)

        return jax.value_and_grad(f)(params)

    def _build_train_step(self):
        tx = self._spec.optimizer
        accum = self._accum_steps

        def step(params, opt_state, features, labels, weights):
            if accum == 1:
                loss, grads = self._loss_and_grads(
                    params, features, labels, weights
                )
            else:
                def body(carry, microbatch):
                    acc_grads, acc_loss = carry
                    f, l, w = microbatch
                    loss, grads = self._loss_and_grads(params, f, l, w)
                    acc_grads = jax.tree_util.tree_map(
                        jnp.add, acc_grads, grads
                    )
                    return (acc_grads, acc_loss + loss), None

                zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
                (grads, loss_sum), _ = jax.lax.scan(
                    body, (zeros, 0.0), (features, labels, weights)
                )
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                loss = loss_sum / accum
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._raw_step = step
        if self._mesh is None:
            return jax.jit(step, donate_argnums=(0, 1))
        rep = self._replicated
        opt_sharding = self._opt_out_shardings() if self._zero1 else rep
        if self._accum_steps == 1:
            batch_in = self._batch_sharding
        else:
            # [accum, micro, ...]: shard the microbatch axis.
            batch_in = NamedSharding(
                self._mesh, P(None, self._data_axis)
            )
        weights_in = (
            self._batch_sharding if self._accum_steps == 1
            else NamedSharding(self._mesh, P(None, self._data_axis))
        )
        return jax.jit(
            step,
            in_shardings=(rep, opt_sharding, batch_in, batch_in,
                          weights_in),
            out_shardings=(rep, opt_sharding, rep),
            donate_argnums=(0, 1),
        )

    def build_fused_steps(self, num_steps):
        """Compile num_steps optimizer steps into ONE XLA program over a
        fixed device-resident batch — the steps-per-loop pattern that
        amortizes host dispatch latency on TPU.  Returns
        fn(params, opt_state, features, labels, weights) ->
        (params, opt_state, last_loss)."""
        raw = self._raw_step

        def multi(params, opt_state, features, labels, weights):
            def body(_i, carry):
                params, opt_state, _ = carry
                return raw(params, opt_state, features, labels, weights)

            return jax.lax.fori_loop(
                0, num_steps, body, (params, opt_state, jnp.float32(0))
            )

        if self._mesh is None:
            return jax.jit(multi, donate_argnums=(0, 1))
        rep = self._replicated
        opt_sharding = self._opt_out_shardings() if self._zero1 else rep
        return jax.jit(
            multi,
            in_shardings=(rep, opt_sharding, self._batch_sharding,
                          self._batch_sharding, self._batch_sharding),
            out_shardings=(rep, opt_sharding, rep),
            donate_argnums=(0, 1),
        )

    def _build_eval_step(self):
        apply_fn = self._spec.apply_fn

        def step(params, features):
            return apply_fn(params, features, False)

        if self._mesh is None:
            return jax.jit(step)
        return jax.jit(
            step,
            in_shardings=(self._replicated, self._batch_sharding),
            out_shardings=self._replicated,
        )

    # -- Trainer API --------------------------------------------------------

    def _padded(self, features, labels, total):
        (features, labels), weights = _pad_batch((features, labels), total)
        return features, labels, weights

    def train_minibatch(self, features, labels):
        if self._example_features is None:
            # Shape/dtype skeleton of one raw minibatch — fixes the
            # serving signature of the train-end servable export.
            self._example_features = jax.tree_util.tree_map(
                lambda a: np.zeros(np.shape(a), np.asarray(a).dtype),
                features,
            )
        with self.timing.timeit("batch_process"):
            # Each process pads ITS local minibatch to its share of the
            # global batch; _globalize assembles the global array in
            # the multi-controller case (no-op single-process).
            procs = self.process_count
            if self._accum_steps == 1:
                local = self._batch_size * (
                    self.global_device_count // procs
                )
                features, labels, weights = self._padded(
                    features, labels, local
                )
                features = self._globalize(features, self._batch_sharding)
                labels = self._globalize(labels, self._batch_sharding)
                weights = self._globalize(weights, self._batch_sharding)
            else:
                micro = self._batch_size * (
                    self.global_device_count // procs
                )
                local = micro * self._accum_steps
                features, labels, weights = self._padded(
                    features, labels, local
                )
                reshape = lambda a: np.asarray(a).reshape(
                    (self._accum_steps, micro) + np.asarray(a).shape[1:]
                )
                features = jax.tree_util.tree_map(reshape, features)
                labels = jax.tree_util.tree_map(reshape, labels)
                weights = weights.reshape(self._accum_steps, micro)
                accum_sharding = NamedSharding(
                    self._mesh, P(None, self._data_axis)
                ) if self._mesh is not None else None
                features = self._globalize(features, accum_sharding)
                labels = self._globalize(labels, accum_sharding)
                weights = self._globalize(weights, accum_sharding)
            self._params, self._opt_state, loss = self._train_step(
                self._params, self._opt_state, features, labels, weights
            )
        self._version += 1
        self._maybe_report_and_checkpoint()
        return float(loss), self._version

    def _maybe_report_and_checkpoint(self):
        if (
            self._mc is not None
            and self._report_version_steps
            and self._version % self._report_version_steps == 0
        ):
            self._mc.report_version(self._version)
        if (
            self._checkpoint_saver is not None
            and self._checkpoint_steps
            and self._version % self._checkpoint_steps == 0
        ):
            self.save_checkpoint()

    def _forward_local(self, features):
        """Inference on THIS process only: local device, local copy of
        the replicated params.  Eval/predict tasks are handed to
        individual workers by the task stream, so in a multi-controller
        world they must never enter a collective — a lone worker doing
        an eval task would deadlock every peer (the reference's
        allreduce mode evaluates locally for the same reason).  The
        host params copy is cached per model version (an eval task
        runs many minibatches against unchanging params)."""
        if getattr(self, "_local_eval_step", None) is None:
            apply_fn = self._spec.apply_fn
            self._local_eval_step = jax.jit(
                lambda p, x: apply_fn(p, x, False)
            )
            self._local_params_cache = None
        cache = getattr(self, "_local_params_cache", None)
        if cache is None or cache[0] != self._version:
            cache = (self._version, to_numpy(self._params))
            self._local_params_cache = cache
        return self._local_eval_step(cache[1], features)

    def evaluate_minibatch(self, features, labels):
        n = jax.tree_util.tree_leaves(features)[0].shape[0]
        if self.process_count > 1:
            features, _, _ = self._padded(
                features, labels, self._batch_size)
            outputs = self._forward_local(features)
        else:
            total = self._batch_size * self.global_device_count
            features, _, _ = self._padded(features, labels, total)
            outputs = self._eval_step(self._params, features)
        outputs = np.asarray(outputs)[:n]
        return outputs, np.asarray(labels)

    def predict_minibatch(self, features):
        n = jax.tree_util.tree_leaves(features)[0].shape[0]
        if self.process_count > 1:
            padded, _ = _pad_batch(features, self._batch_size)
            return np.asarray(self._forward_local(padded))[:n]
        total = self._batch_size * self.global_device_count
        leaves = jax.tree_util.tree_leaves(features)
        weights = None
        if leaves[0].shape[0] != total:
            features, weights = _pad_batch(features, total)
        outputs = self._eval_step(self._params, features)
        return np.asarray(outputs)[:n]

    # -- state --------------------------------------------------------------

    @property
    def version(self):
        return self._version

    @property
    def params(self):
        return self._params

    def set_params(self, params):
        self._params = params
        self._opt_state = self._spec.optimizer.init(params)
        if self._mesh is not None:
            self._params = jax.device_put(
                to_numpy(self._params), self._replicated
            )
            self._opt_state = self._place_opt_state(
                to_numpy(self._opt_state)
            )

    def export_parameters(self):
        named, _ = flatten_with_names(to_numpy(self._params))
        return named

    def serving_bundle(self):
        """(inference_fn, params, example_input) for the servable
        export; None before the first minibatch fixed the signature."""
        if self._example_features is None:
            return None
        apply_fn = self._spec.apply_fn
        return (
            lambda p, x: apply_fn(p, x, False),
            to_numpy(self._params),
            self._example_features,
        )

    def save_checkpoint(self):
        """Params AND optimizer state (``opt/``-prefixed, mirroring
        spmd_trainer) — an elastic restore must resume the Adam/momentum
        trajectory, not restart it (reference PS slot persistence,
        go/pkg/ps/checkpoint.go:98-133).

        The device->host gather is synchronous (the next step's buffer
        donation invalidates the old arrays), but the disk write runs on
        a single background thread so the train loop only ever pays
        transfer time, not serialization+IO.  ``flush_checkpoints``
        joins pending writes (called at train end)."""
        with self.timing.timeit("checkpoint_save"):
            payload = dict(self.export_parameters())
            opt_named, _ = flatten_with_names(to_numpy(self._opt_state))
            payload.update({"opt/" + k: v for k, v in opt_named.items()})
            if self._ckpt_executor is None:
                from concurrent.futures import ThreadPoolExecutor

                self._ckpt_executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ckpt-write"
                )
            # Join the previous write first: bounds outstanding host
            # copies to one and guarantees its error (disk full, NFS)
            # surfaces HERE — raising out of train_minibatch so the
            # task fails visibly, exactly like the old synchronous save.
            self._surface_checkpoint_errors(wait=True)
            self._ckpt_future = self._ckpt_executor.submit(
                self._checkpoint_saver.save, self._version, dense=payload
            )
        logger.info("checkpoint at version %d queued for write",
                    self._version)

    def _surface_checkpoint_errors(self, wait):
        future = getattr(self, "_ckpt_future", None)
        if future is None:
            return
        if wait or future.done():
            self._ckpt_future = None
            try:
                future.result()
            except Exception as e:  # noqa: BLE001 — IO errors
                raise RuntimeError(
                    "async checkpoint write failed: %s" % (e,)
                ) from e

    def flush_checkpoints(self):
        """Join pending checkpoint writes AND retire the writer thread
        (train end / before export).  Shutting the executor down here —
        not just joining the future — is the owner's stop path (EL007):
        a lazily re-created pool costs nothing, but a leaked one keeps
        its thread alive past the trainer and can hang worker exit.
        The next async save simply recreates it."""
        try:
            self._surface_checkpoint_errors(wait=True)
        finally:
            # Retire the pool even when the surfaced write error
            # raises — the failure path must not leak the thread.
            if self._ckpt_executor is not None:
                self._ckpt_executor.shutdown(wait=True)
                self._ckpt_executor = None

    def init_from_checkpoint(self):
        if self._checkpoint_saver is None:
            return False
        self.flush_checkpoints()
        try:
            dense, _, version = self._checkpoint_saver.load()
        except FileNotFoundError:
            return False
        from elasticdl_tpu.utils.pytree import unflatten_from_names

        params_named = {
            k: v for k, v in dense.items() if not k.startswith("opt/")
        }
        opt_named = {
            k[len("opt/"):]: v for k, v in dense.items()
            if k.startswith("opt/")
        }
        self._params = unflatten_from_names(
            to_numpy(self._params), params_named
        )
        fresh_opt = True
        if opt_named:
            try:
                self._opt_state = unflatten_from_names(
                    to_numpy(self._opt_state), opt_named
                )
                fresh_opt = False
            except (KeyError, ValueError) as e:
                # Optimizer changed since the checkpoint (e.g. Adam ->
                # momentum): params are still good, trajectory is not.
                logger.warning(
                    "checkpoint optimizer state incompatible (%s); "
                    "re-initializing optimizer", e,
                )
        if fresh_opt:  # pre-opt-state checkpoint or structure mismatch
            self._opt_state = self._spec.optimizer.init(self._params)
        if self._mesh is not None:
            self.rebuild(self._mesh)
        self._version = version
        logger.info("restored checkpoint version %d", version)
        return True
