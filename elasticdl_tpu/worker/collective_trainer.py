"""Collective data-parallel trainer — the TPU-native AllReduce path.

Replaces the reference's Horovod/Gloo AllReduce trainer
(elasticdl/python/worker/allreduce_trainer.py:37-146) with a jitted train
step over a ``jax.sharding.Mesh``: the batch is sharded on the ``data`` axis,
parameters are replicated, and XLA inserts the gradient all-reduce over ICI.
Fixed-global-batch elasticity (reference
elasticai_api/pytorch/optimizer.py:136-169) becomes a ``lax.scan`` gradient
accumulation over microbatches, re-jitted when the accumulation count
changes with the world size.  Rebuilding for a new mesh = re-sharding params
and re-jitting — the compile cache keyed by (mesh shape, accum steps).

``--zero1`` swaps the weight update for ZeRO-1 cross-replica sharding
(worker/zero.py, docs/training_pipeline.md): optimizer state lives as
flat padded 1-D shards over the data axis (per-device optimizer memory
~1/N), the update runs shard-locally between an explicit
reduce-scatter/all-gather pair, and a world re-form re-partitions the
live shards device-to-device with Adam moments preserved bit-exactly.
"""


import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.pytree import flatten_with_names, to_numpy
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.fused_driver import PreparedBatch, StagedWindow
from elasticdl_tpu.worker.trainer import Trainer
from elasticdl_tpu.worker.zero import ZeroPartitioner

logger = get_logger(__name__)

# prepare_batch plan cache cap: keys are (record count, tree structure)
# — one full-batch entry plus a handful of tail-batch sizes per task
# shape, so the cache only grows past this if batch shapes churn.
_PAD_PLAN_CACHE_MAX = 32


class _PadPlan:
    """Host-side batch-prep plan, derived ONCE per (record count, tree
    structure) instead of re-deriving ``np.asarray``/shape math inside
    every ``train_minibatch`` (the per-step hot loop's host tax).

    Holds per-leaf pad widths (None = no pad), per-leaf accum reshape
    targets (None = no reshape), and the loss-mask weights array.  The
    weights array is shared read-only across steps — every consumer
    (device_put, np.stack) copies, never mutates.
    """

    __slots__ = ("pad_widths", "reshapes", "weights", "local")

    def __init__(self, leaves, n, local, accum, micro):
        if n > local:
            raise ValueError(
                "minibatch has %d records > trainer's global batch %d"
                % (n, local)
            )
        pad = local - n
        self.local = local
        self.pad_widths = [
            [(0, pad)] + [(0, 0)] * (np.asarray(leaf).ndim - 1)
            if pad else None
            for leaf in leaves
        ]
        if accum > 1:
            self.reshapes = [
                (accum, micro) + tuple(np.shape(leaf)[1:])
                for leaf in leaves
            ]
        else:
            self.reshapes = [None] * len(leaves)
        weights = np.zeros((local,), dtype=np.float32)
        weights[:n] = 1.0
        if accum > 1:
            weights = weights.reshape(accum, micro)
        self.weights = weights


def _masked_mean(per_example, weights):
    per_example = per_example.reshape(per_example.shape[0], -1).mean(axis=-1)
    return jnp.sum(per_example * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def _pad_batch(tree, batch_size):
    """Pad every leaf to batch_size rows; returns (padded, weights)."""
    leaves = jax.tree_util.tree_leaves(tree)
    n = leaves[0].shape[0]
    if n > batch_size:
        raise ValueError(
            "minibatch has %d records > trainer's global batch %d"
            % (n, batch_size)
        )
    weights = np.zeros((batch_size,), dtype=np.float32)
    weights[:n] = 1.0
    if n == batch_size:
        return tree, weights

    def pad(a):
        a = np.asarray(a)
        pad_width = [(0, batch_size - n)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, pad_width)

    return jax.tree_util.tree_map(pad, tree), weights


class CollectiveTrainer(Trainer):
    def __init__(
        self,
        spec,
        batch_size,
        mesh=None,
        data_axis="data",
        accum_steps=1,
        rng_seed=0,
        master_client=None,
        report_version_steps=0,
        checkpoint_saver=None,
        checkpoint_steps=0,
        use_bf16_compute=False,
        zero1=False,
        exporter=None,
        export_steps=0,
    ):
        self._spec = spec
        self._batch_size = batch_size
        self._data_axis = data_axis
        self._accum_steps = accum_steps
        self._mc = master_client
        self._report_version_steps = report_version_steps
        self._checkpoint_saver = checkpoint_saver
        self._checkpoint_steps = checkpoint_steps
        # Continuous servable export (the online-learning loop's trainer
        # half, docs/serving.md): every export_steps optimizer steps a
        # complete versioned servable lands at the exporter's base for
        # the aggregation tier to ingest.  Worker-0-only, same guard as
        # checkpointing (worker/main zeroes export_steps elsewhere).
        self._exporter = exporter
        self._export_steps = export_steps if exporter is not None else 0
        self._use_bf16_compute = use_bf16_compute
        # ZeRO-1: shard optimizer state over the data axis instead of
        # replicating it — Adam moments cost 2x params, so an 8-way dp
        # mesh drops per-device optimizer memory ~8x (no reference
        # counterpart — deliberate beyond-reference design, SURVEY
        # §2.12).  Every optimizer leaf is flattened to 1-D, padded to
        # a multiple of the shard count, and sharded on dim 0
        # (worker/zero.py), so coverage is total regardless of leaf
        # shape; the train step updates only the local shard and
        # all-gathers fresh params (docs/training_pipeline.md).
        self._zero1 = zero1
        self._zero = None          # active partitioner (mesh worlds)
        self._opt_is_flat = False  # opt-state representation marker
        self.timing = Timing(logger=logger)
        self._version = 0
        self._ckpt_executor = None
        self._ckpt_future = None
        self._export_future = None
        self._example_features = None

        params = spec.init_fn(jax.random.PRNGKey(rng_seed))
        self._opt_state = spec.optimizer.init(params)
        self._params = params
        self._mesh = None
        self.rebuild(mesh)

    # -- mesh / jit management ---------------------------------------------

    def snapshot_to_host(self):
        """Pull params + optimizer state to host numpy, in place.

        Called by the elastic controller BEFORE re-forming a
        master-coordinated world: the re-init clears XLA backends, which
        invalidates every device array of the old epoch.  Replicated
        leaves always survive (each process holds a full copy).  A
        ZeRO-1 state is gathered through its unpadding view as a jitted
        on-device all-gather FIRST (``ZeroPartitioner.gather_to_host``),
        so even in a multi-controller world every process holds the full
        original-shape value before the host transfer — ``to_numpy`` on
        a raw sharded leaf would hit non-addressable shards.  Only when
        that gather itself fails (a peer died mid-epoch and took its
        shards with it) is optimizer state re-initialized from the
        (still complete) params — the same information loss the
        reference accepts when a Horovod restart reloads the last
        checkpoint without optimizer slots."""
        try:
            self._params = to_numpy(self._params)
        except Exception as e:
            raise RuntimeError(
                "parameters are not locally addressable; cannot "
                "survive a world change without a checkpoint restore"
            ) from e
        try:
            if self._opt_is_flat and self._zero is not None:
                self._opt_state = self._zero.gather_to_host(
                    self._opt_state
                )
            else:
                self._opt_state = to_numpy(self._opt_state)
        except Exception:  # noqa: BLE001 — lost ZeRO-1 shards
            logger.warning(
                "optimizer state not locally addressable (ZeRO-1 "
                "shards lost with a dead peer); re-initializing "
                "optimizer moments from params"
            )
            self._opt_state = self._spec.optimizer.init(self._params)
        self._opt_is_flat = False

    def rebuild(self, mesh):
        """(Re)shard state and (re)compile steps for a (new) mesh.

        This is the elastic-resize path: called at init and whenever the
        rendezvous epoch changes the device world.  State placement is
        device-to-device whenever the arrays are live on a surviving
        backend (``jax.device_put`` re-shards committed arrays across
        mesh shapes without a host round-trip; ZeRO-1 shards re-pad for
        the new shard count bit-exactly via
        ``ZeroPartitioner.repartition``); the host bounce survives only
        as the fallback for the multi-controller path, where the world
        re-init already cleared the backend and the controller
        snapshotted state to host numpy first.
        """
        # The elastic re-form as one span in the worker's trace
        # (docs/observability.md): epoch re-forms, device counts, and
        # reshard cost line up against the rest of the incident.
        with tracing.span(
            "worker.world_reform",
            devices=0 if mesh is None else mesh.devices.size,
            zero1=bool(self._zero1),
        ):
            self._rebuild_traced(mesh)

    def _rebuild_traced(self, mesh):
        old_zero = self._zero if self._opt_is_flat else None
        self._mesh = mesh
        # Mesh/accum-dependent caches: pad plans bake in the local batch
        # geometry, fused windows bake in shardings — both die with the
        # old world.
        self._pad_plans = {}
        self._fused_window_cache = {}
        if mesh is not None:
            replicated = NamedSharding(mesh, P())
            self._batch_sharding = NamedSharding(mesh, P(self._data_axis))
            self._replicated = replicated
            self._zero = (
                ZeroPartitioner(
                    self._spec.optimizer, self._params, mesh,
                    self._data_axis,
                )
                if self._zero1 else None
            )
            with self.timing.timeit("state_reshard"):
                self._params = self._reshard_to(
                    self._params, replicated
                )
                self._opt_state = self._place_opt_state(old_zero)
            self._opt_is_flat = self._zero is not None
            if self._zero is not None:
                self._log_zero1_placement()
        else:
            if old_zero is not None:  # leaving the mesh world entirely
                self._opt_state = old_zero.gather_to_host(
                    self._opt_state
                )
            self._opt_is_flat = False
            self._zero = None
            self._batch_sharding = None
            self._replicated = None
        self._train_step = self._build_train_step()
        self._eval_step = self._build_eval_step()
        self._local_eval_step = None  # rebuilt lazily: the old one may
        # belong to a cleared backend (world change)

    def _reshard_to(self, tree, sharding):
        """Place a pytree under ``sharding``, device-to-device when the
        leaves are live device arrays (a committed array re-shards
        across meshes without leaving the device fabric), straight
        host->device when they are numpy.  Falls back to an explicit
        host bounce only when the direct put fails (arrays from a
        cleared backend that were never snapshotted)."""
        def put(leaf):
            if isinstance(leaf, jax.Array):
                # A leaf already under the target sharding is a
                # placement no-op — only book actual moves.
                if getattr(leaf, "sharding", None) != sharding:
                    self.timing.bump(
                        "reshard_device_bytes", leaf.nbytes
                    )
            else:
                self.timing.bump(
                    "reshard_host_bytes", np.asarray(leaf).nbytes
                )
            return jax.device_put(leaf, sharding)

        try:
            return jax.tree_util.tree_map(put, tree)
        except Exception:  # noqa: BLE001 — dead backend arrays
            logger.warning(
                "device-to-device reshard unavailable; host bounce"
            )
            self.timing.bump("reshard_host_fallbacks")
            return jax.device_put(to_numpy(tree), sharding)

    def _place_opt_state(self, old_zero):
        """Place the optimizer state for the current mesh/partitioner.

        Live flat shards from a previous world re-partition
        device-to-device (Adam moments preserved bit-exactly, see
        ZeroPartitioner.repartition); original-shape state (first
        build, post-snapshot, post-restore) is flattened host-side and
        placed sharded; with zero1 off the state is simply (re)placed
        replicated.  A dead-backend failure re-initializes moments from
        params — the snapshot_to_host contract."""
        state = self._opt_state
        if self._zero is None:
            return self._reshard_to(state, self._replicated)
        try:
            if old_zero is not None:
                return self._zero.repartition(
                    state, old_zero, timing=self.timing
                )
            return self._zero.place_state(to_numpy(state))
        except Exception:  # noqa: BLE001 — dead backend / lost shards
            logger.warning(
                "zero1: live shard repartition failed; attempting "
                "host bounce"
            )
            self.timing.bump("reshard_host_fallbacks")
            try:
                if old_zero is not None:
                    state = old_zero.gather_to_host(state)
                return self._zero.place_state(
                    jax.tree_util.tree_map(np.asarray, state)
                )
            except Exception:  # noqa: BLE001
                logger.warning(
                    "zero1: optimizer shards unrecoverable; "
                    "re-initializing moments from params"
                )
                return self._zero.place_state(
                    self._spec.optimizer.init(to_numpy(self._params))
                )

    def _opt_out_shardings(self):
        """Opt-state placement for jit in/out_shardings: the ZeRO-1
        per-leaf tree when sharding is on, plain replicated otherwise
        (the exact old path)."""
        if self._zero is not None:
            return self._zero.state_shardings(self._opt_state)
        return self._replicated

    def zero1_report(self):
        """Per-device optimizer-state byte accounting, both modes.

        Returns {mode, num_shards, per_device_bytes,
        replicated_equiv_bytes, reduction_factor, padding_bytes,
        scalar_leaves_replicated}; None without a mesh."""
        if self._mesh is None:
            return None
        if self._zero is None:
            total = sum(
                getattr(leaf, "nbytes", None)
                or np.asarray(leaf).nbytes
                for leaf in jax.tree_util.tree_leaves(self._opt_state)
            )
            return {
                "mode": "replicated",
                "num_shards": int(self._mesh.shape[self._data_axis]),
                "per_device_bytes": int(total),
                "replicated_equiv_bytes": int(total),
                "reduction_factor": 1.0,
                "padding_bytes": 0,
                "scalar_leaves_replicated": 0,
            }
        replicated, sharded, padding = self._zero.state_bytes(
            self._opt_state
        )
        return {
            "mode": "zero1",
            "num_shards": self._zero.num_shards,
            "per_device_bytes": int(sharded),
            "replicated_equiv_bytes": int(replicated),
            "reduction_factor": replicated / max(1, sharded),
            "padding_bytes": int(padding),
            "scalar_leaves_replicated": sum(
                1 for s in self._zero.state_specs if s.padded == 0
            ),
        }

    def _log_zero1_placement(self):
        report = self.zero1_report()
        logger.info(
            "zero1: optimizer state sharded %d ways — %.3f MiB/device "
            "(replicated would be %.3f MiB/device, %.1fx reduction; "
            "%d padding bytes, %d scalar leaves replicated)",
            report["num_shards"],
            report["per_device_bytes"] / 2**20,
            report["replicated_equiv_bytes"] / 2**20,
            report["reduction_factor"],
            report["padding_bytes"],
            report["scalar_leaves_replicated"],
        )

    @property
    def global_device_count(self):
        return self._mesh.size if self._mesh is not None else 1

    @property
    def process_count(self):
        """Number of processes the mesh spans (1 = single-controller)."""
        if self._mesh is None:
            return 1
        return len({d.process_index for d in self._mesh.devices.flat})

    def _globalize(self, tree, sharding):
        """Assemble per-process local batches into global arrays.

        Multi-controller SPMD: every process holds ITS share of the
        global batch (its own task stream's records); the global array
        is the concatenation over processes along the data axis.  The
        single-process path hands numpy straight to jit (placement via
        in_shardings) — identical math, no assembly step."""
        if self.process_count == 1:
            return tree
        return jax.tree_util.tree_map(
            lambda a: jax.make_array_from_process_local_data(
                sharding, np.asarray(a)
            ),
            tree,
        )


    def set_accum_steps(self, accum_steps):
        if accum_steps != self._accum_steps:
            self._accum_steps = accum_steps
            self._pad_plans = {}
            self._fused_window_cache = {}
            self._train_step = self._build_train_step()

    def _loss_and_grads(self, params, features, labels, weights):
        apply_fn = self._spec.apply_fn
        loss_fn = self._spec.loss_fn

        def f(p):
            x = features
            if self._use_bf16_compute:
                # Cast params AND activations: flax promotes mixed
                # bf16-param/f32-input matmuls back to f32, which would
                # silently keep the MXU off the bf16 path.
                to_bf16 = lambda a: (
                    a.astype(jnp.bfloat16)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a
                )
                p = jax.tree_util.tree_map(to_bf16, p)
                x = jax.tree_util.tree_map(to_bf16, x)
            out = apply_fn(p, x, True)
            per_example = loss_fn(out, labels).astype(jnp.float32)
            return _masked_mean(per_example, weights)

        return jax.value_and_grad(f)(params)

    def _zero1_apply(self, tx, params, opt_state, grads):
        """ZeRO-1 weight update: reduce-scatter(grads) -> shard-local
        optimizer apply -> all-gather(params), expressed as sharding
        constraints on the flat padded views (traceable; used inside
        the jitted step).

        Two numerics pins make the trajectory BIT-IDENTICAL to the
        replicated path (measured over 100 steps, bench_zero.py), which
        is what lets the elastic churn drills verify zero1 worlds
        exactly:

        1. grads are first constrained replicated — the cross-replica
           sum lands at the same program point as the replicated path's
           all-reduce, so the backward is never re-partitioned into a
           different accumulation order.  The flat sharded constraint
           right after is then a pure shard slice; on TPU, XLA's
           reduce-scatter creator folds the sum+slice pair into a true
           reduce-scatter.
        2. an optimization barrier between the shard-local update and
           the params all-gather — without it the partitioner
           duplicates the update computation (one sharded copy for the
           opt-state output, one differently-fused replicated copy for
           the params output) and the copies disagree in the last ulp.

        The scan carry of a fused window shrinks accordingly: opt state
        rides through the window as 1/N-sized shards.
        """
        z = self._zero
        shard_t = z.params_shardings(z.shard)
        rep_t = z.params_shardings(z.replicated)
        grads = jax.lax.with_sharding_constraint(grads, rep_t)
        flat_g = jax.lax.with_sharding_constraint(
            z.flatten_params(grads), shard_t
        )
        flat_p = jax.lax.with_sharding_constraint(
            z.flatten_params(params), shard_t
        )
        updates, opt_state = tx.update(flat_g, opt_state, flat_p)
        flat_new = optax.apply_updates(flat_p, updates)
        flat_new, opt_state = jax.lax.optimization_barrier(
            (flat_new, opt_state)
        )
        flat_new = jax.lax.with_sharding_constraint(flat_new, rep_t)
        return z.unflatten_params(flat_new), opt_state

    def _build_train_step(self):
        tx = self._spec.optimizer
        accum = self._accum_steps
        zero = self._zero

        def step(params, opt_state, features, labels, weights):
            if accum == 1:
                loss, grads = self._loss_and_grads(
                    params, features, labels, weights
                )
            else:
                def body(carry, microbatch):
                    acc_grads, acc_loss = carry
                    f, l, w = microbatch
                    loss, grads = self._loss_and_grads(params, f, l, w)
                    acc_grads = jax.tree_util.tree_map(
                        jnp.add, acc_grads, grads
                    )
                    return (acc_grads, acc_loss + loss), None

                zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
                (grads, loss_sum), _ = jax.lax.scan(
                    body, (zeros, 0.0), (features, labels, weights)
                )
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                loss = loss_sum / accum
            if zero is not None:
                params, opt_state = self._zero1_apply(
                    tx, params, opt_state, grads
                )
            else:
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._raw_step = step
        if self._mesh is None:
            return jax.jit(step, donate_argnums=(0, 1))
        rep = self._replicated
        opt_sharding = self._opt_out_shardings()
        if self._accum_steps == 1:
            batch_in = self._batch_sharding
        else:
            # [accum, micro, ...]: shard the microbatch axis.
            batch_in = NamedSharding(
                self._mesh, P(None, self._data_axis)
            )
        weights_in = (
            self._batch_sharding if self._accum_steps == 1
            else NamedSharding(self._mesh, P(None, self._data_axis))
        )
        return jax.jit(
            step,
            in_shardings=(rep, opt_sharding, batch_in, batch_in,
                          weights_in),
            out_shardings=(rep, opt_sharding, rep),
            donate_argnums=(0, 1),
        )

    def build_fused_steps(self, num_steps):
        """Compile num_steps optimizer steps into ONE XLA program over a
        fixed device-resident batch — the steps-per-loop pattern that
        amortizes host dispatch latency on TPU.  Returns
        fn(params, opt_state, features, labels, weights) ->
        (params, opt_state, last_loss)."""
        raw = self._raw_step

        def multi(params, opt_state, features, labels, weights):
            def body(_i, carry):
                params, opt_state, _ = carry
                return raw(params, opt_state, features, labels, weights)

            return jax.lax.fori_loop(
                0, num_steps, body, (params, opt_state, jnp.float32(0))
            )

        if self._mesh is None:
            return jax.jit(multi, donate_argnums=(0, 1))
        rep = self._replicated
        opt_sharding = self._opt_out_shardings()
        return jax.jit(
            multi,
            in_shardings=(rep, opt_sharding, self._batch_sharding,
                          self._batch_sharding, self._batch_sharding),
            out_shardings=(rep, opt_sharding, rep),
            donate_argnums=(0, 1),
        )

    def _window_batch_sharding(self):
        """Sharding for window-stacked batch leaves: [K, batch, ...]
        shards dim 1 (the data axis); with accumulation the stack is
        [K, accum, micro, ...] and dim 2 is the data axis."""
        if self._mesh is None:
            return None
        if self._accum_steps == 1:
            return NamedSharding(self._mesh, P(None, self._data_axis))
        return NamedSharding(
            self._mesh, P(None, None, self._data_axis)
        )

    def build_fused_window(self, num_steps):
        """Compile num_steps optimizer steps over num_steps DISTINCT
        minibatches (stacked on the leading axis) into ONE XLA program —
        the production fused-step path (``build_fused_steps`` reuses a
        single device-resident batch and exists for the bench).

        Returns fn(params, opt_state, features, labels, weights) ->
        (params, opt_state, losses[num_steps]); losses stay on device
        until the caller fetches them (fused_driver.LossRing).

        The scan is fully UNROLLED: a rolled scan double-buffers the
        params/opt-state carry every iteration (measured ~4x slower
        than sequential dispatch on CPU XLA), while the unrolled body
        is one straight-line program XLA fuses across steps (~2.4x
        faster than the per-step loop on the same rig).  Compile time
        scales with num_steps — keep --fused_steps modest (4-16); each
        distinct window length compiles once and is cached.

        With ``--zero1`` the window's opt-state carry is the flat
        sharded form: each chained step hands its successor 1/N of the
        optimizer state instead of a full replicated copy, which is
        what shrinks the rolled-scan carry-copy cost the fused driver
        measured (docs/training_pipeline.md has the carry-size math).
        """
        raw = self._raw_step

        def window(params, opt_state, features, labels, weights):
            def body(carry, batch):
                params, opt_state = carry
                f, l, w = batch
                params, opt_state, loss = raw(params, opt_state, f, l, w)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), (features, labels, weights),
                unroll=True,
            )
            return params, opt_state, losses

        if self._mesh is None:
            return jax.jit(window, donate_argnums=(0, 1))
        rep = self._replicated
        opt_sharding = self._opt_out_shardings()
        batch_in = self._window_batch_sharding()
        return jax.jit(
            window,
            in_shardings=(rep, opt_sharding, batch_in, batch_in,
                          batch_in),
            out_shardings=(rep, opt_sharding, rep),
            donate_argnums=(0, 1),
        )

    def _build_eval_step(self):
        apply_fn = self._spec.apply_fn

        def step(params, features):
            return apply_fn(params, features, False)

        if self._mesh is None:
            return jax.jit(step)
        return jax.jit(
            step,
            in_shardings=(self._replicated, self._batch_sharding),
            out_shardings=self._replicated,
        )

    # -- Trainer API --------------------------------------------------------

    def _padded(self, features, labels, total):
        (features, labels), weights = _pad_batch((features, labels), total)
        return features, labels, weights

    def _accum_sharding(self):
        """Per-step batch sharding: [batch, ...] over data, or
        [accum, micro, ...] with the microbatch axis over data."""
        if self._mesh is None:
            return None
        if self._accum_steps == 1:
            return self._batch_sharding
        return NamedSharding(self._mesh, P(None, self._data_axis))

    def prepare_batch(self, features, labels, count=None):
        """Host-side batch prep (pad, accum reshape, multi-controller
        globalize) via a cached per-(count, structure) plan — the
        producer-stage half of the fused driver; ``train_minibatch``
        routes through it too, so the per-step path stops re-deriving
        shapes every step."""
        if self._example_features is None:
            # Shape/dtype skeleton of one raw minibatch — fixes the
            # serving signature of the train-end servable export.
            self._example_features = jax.tree_util.tree_map(
                lambda a: np.zeros(np.shape(a), np.asarray(a).dtype),
                features,
            )
        with self.timing.timeit("batch_prep"):
            leaves, treedef = jax.tree_util.tree_flatten(
                (features, labels)
            )
            n = int(np.shape(leaves[0])[0])
            # Trailing dims are part of the key: with accumulation the
            # plan bakes reshape targets, and a pipeline with variable
            # trailing shapes (e.g. sequence length) must not hit a
            # stale plan's targets.
            key = (n, treedef,
                   tuple(np.shape(leaf)[1:] for leaf in leaves))
            plan = self._pad_plans.get(key)
            if plan is None:
                procs = self.process_count
                micro = self._batch_size * (
                    self.global_device_count // procs
                )
                local = micro * self._accum_steps
                plan = _PadPlan(
                    leaves, n, local, self._accum_steps, micro
                )
                if len(self._pad_plans) >= _PAD_PLAN_CACHE_MAX:
                    self._pad_plans.clear()
                self._pad_plans[key] = plan
            out = []
            for leaf, pad_width, reshape in zip(
                leaves, plan.pad_widths, plan.reshapes
            ):
                a = np.asarray(leaf)
                if pad_width is not None:
                    a = np.pad(a, pad_width)
                if reshape is not None:
                    a = a.reshape(reshape)
                out.append(a)
            features, labels = jax.tree_util.tree_unflatten(treedef, out)
            weights = plan.weights
            if self.process_count > 1:
                sharding = self._accum_sharding()
                features = self._globalize(features, sharding)
                labels = self._globalize(labels, sharding)
                weights = self._globalize(weights, sharding)
        return PreparedBatch(
            features, labels, weights, n if count is None else count
        )

    def train_minibatch(self, features, labels):
        """One step; returns (loss, version) where ``loss`` is a LAZY
        device scalar — no host sync here.  Callers that need a float
        (cadence logging, benches) pull it explicitly via
        ``float(loss)``; that fetch is the fence."""
        prepared = self.prepare_batch(features, labels)
        with self.timing.timeit("step_dispatch"):
            self._params, self._opt_state, loss = self._train_step(
                self._params, self._opt_state,
                prepared.features, prepared.labels, prepared.weights,
            )
        self._count_zero1_traffic(1)
        self._version += 1
        self._maybe_report_and_checkpoint()
        return loss, self._version

    def _count_zero1_traffic(self, steps):
        """Logical collective payload accounting: each zero1 step
        reduce-scatters one flat grads tree and all-gathers one flat
        params tree (byte counts are the annotated payload sizes, not
        a wire capture — surfaced under Timing.summary()['zero1'])."""
        if self._zero is None:
            return
        flat_bytes = self._zero.flat_param_bytes()
        self.timing.bump("zero1_reduce_scatter_bytes",
                         flat_bytes * steps)
        self.timing.bump("zero1_all_gather_bytes", flat_bytes * steps)

    # -- fused window API (fused_driver.FusedStepDriver) --------------------

    @property
    def max_window(self):
        """None = unbounded fused windows.  Multi-controller batches
        are committed global arrays (per-process assembly) — stacking
        them host-side is impossible, so the driver is capped to
        window 1 there."""
        return 1 if self.process_count > 1 else None

    def steps_to_boundary(self):
        """Steps until the next version-report or checkpoint cadence
        boundary — the fused driver clamps windows to it so those
        events land on exactly the per-step loop's step numbers."""
        dists = []
        if self._mc is not None and self._report_version_steps:
            dists.append(
                self._report_version_steps
                - self._version % self._report_version_steps
            )
        if self._checkpoint_saver is not None and self._checkpoint_steps:
            dists.append(
                self._checkpoint_steps
                - self._version % self._checkpoint_steps
            )
        if self._export_steps:
            dists.append(
                self._export_steps - self._version % self._export_steps
            )
        return min(dists) if dists else None

    def stage_window(self, prepared, to_device=True):
        """Stack K prepared batches on a leading axis and (optionally)
        start their host→device transfer NOW — ``device_put`` is async,
        so staging window N+1 while window N executes is the device
        double-buffer."""
        k = len(prepared)
        if k > 1 and self.process_count > 1:
            raise ValueError(
                "fused windows are single-controller only (max_window)"
            )
        if k == 1:
            batch = prepared[0]
            features, labels = batch.features, batch.labels
            weights = batch.weights
            sharding = self._accum_sharding()
        else:
            stack = lambda *leaves: np.stack(leaves)
            features = jax.tree_util.tree_map(
                stack, *[b.features for b in prepared]
            )
            labels = jax.tree_util.tree_map(
                stack, *[b.labels for b in prepared]
            )
            weights = np.stack([b.weights for b in prepared])
            sharding = self._window_batch_sharding()
        if to_device and self.process_count == 1:
            if sharding is not None:
                put = lambda tree: jax.device_put(tree, sharding)
            else:
                put = jax.device_put
            features, labels, weights = (
                put(features), put(labels), put(weights)
            )
        return StagedWindow(k, features, labels, weights)

    def train_window(self, staged):
        """Dispatch one staged window (1 XLA call for its K steps);
        returns (device-resident losses, version-after-window).  The
        caller is responsible for clamping K to ``steps_to_boundary``
        (fused_driver does) — report/checkpoint cadence checks run once
        at the window boundary."""
        if staged.size == 1:
            with self.timing.timeit("step_dispatch"):
                self._params, self._opt_state, losses = self._train_step(
                    self._params, self._opt_state,
                    staged.features, staged.labels, staged.weights,
                )
        else:
            fn = self._fused_window_cache.get(staged.size)
            if fn is None:
                fn = self.build_fused_window(staged.size)
                self._fused_window_cache[staged.size] = fn
            with self.timing.timeit("step_dispatch"):
                self._params, self._opt_state, losses = fn(
                    self._params, self._opt_state,
                    staged.features, staged.labels, staged.weights,
                )
        self._count_zero1_traffic(staged.size)
        self._version += staged.size
        self._maybe_report_and_checkpoint()
        return losses, self._version

    def _maybe_report_and_checkpoint(self):
        if (
            self._mc is not None
            and self._report_version_steps
            and self._version % self._report_version_steps == 0
        ):
            self._mc.report_version(self._version)
        if (
            self._checkpoint_saver is not None
            and self._checkpoint_steps
            and self._version % self._checkpoint_steps == 0
        ):
            self.save_checkpoint()
        if self._export_steps and self._version % self._export_steps == 0:
            self.export_servable_now()

    def _forward_local(self, features):
        """Inference on THIS process only: local device, local copy of
        the replicated params.  Eval/predict tasks are handed to
        individual workers by the task stream, so in a multi-controller
        world they must never enter a collective — a lone worker doing
        an eval task would deadlock every peer (the reference's
        allreduce mode evaluates locally for the same reason).  The
        host params copy is cached per model version (an eval task
        runs many minibatches against unchanging params)."""
        if getattr(self, "_local_eval_step", None) is None:
            apply_fn = self._spec.apply_fn
            self._local_eval_step = jax.jit(
                lambda p, x: apply_fn(p, x, False)
            )
            self._local_params_cache = None
        cache = getattr(self, "_local_params_cache", None)
        if cache is None or cache[0] != self._version:
            cache = (self._version, to_numpy(self._params))
            self._local_params_cache = cache
        return self._local_eval_step(cache[1], features)

    def evaluate_minibatch(self, features, labels):
        n = jax.tree_util.tree_leaves(features)[0].shape[0]
        if self.process_count > 1:
            features, _, _ = self._padded(
                features, labels, self._batch_size)
            outputs = self._forward_local(features)
        else:
            total = self._batch_size * self.global_device_count
            features, _, _ = self._padded(features, labels, total)
            outputs = self._eval_step(self._params, features)
        outputs = np.asarray(outputs)[:n]
        return outputs, np.asarray(labels)

    def predict_minibatch(self, features):
        n = jax.tree_util.tree_leaves(features)[0].shape[0]
        if self.process_count > 1:
            padded, _ = _pad_batch(features, self._batch_size)
            return np.asarray(self._forward_local(padded))[:n]
        total = self._batch_size * self.global_device_count
        leaves = jax.tree_util.tree_leaves(features)
        weights = None
        if leaves[0].shape[0] != total:
            features, weights = _pad_batch(features, total)
        outputs = self._eval_step(self._params, features)
        return np.asarray(outputs)[:n]

    # -- state --------------------------------------------------------------

    @property
    def version(self):
        return self._version

    @property
    def params(self):
        return self._params

    def set_params(self, params):
        self._params = params
        self._opt_state = self._spec.optimizer.init(params)
        self._opt_is_flat = False
        if self._mesh is not None:
            self._params = self._reshard_to(
                self._params, self._replicated
            )
            self._opt_state = self._place_opt_state(old_zero=None)
            self._opt_is_flat = self._zero is not None

    def export_parameters(self):
        named, _ = flatten_with_names(to_numpy(self._params))
        return named

    def _opt_state_on_host(self):
        """Original-shape HOST view of the optimizer state.  ZeRO-1
        shards are gathered on-device through the unpadding view first
        (multi-controller safe); replicated state converts directly."""
        if self._opt_is_flat and self._zero is not None:
            return self._zero.gather_to_host(self._opt_state)
        return to_numpy(self._opt_state)

    def serving_bundle(self):
        """(inference_fn, params, example_input) for the servable
        export; None before the first minibatch fixed the signature."""
        if self._example_features is None:
            return None
        apply_fn = self._spec.apply_fn
        return (
            lambda p, x: apply_fn(p, x, False),
            to_numpy(self._params),
            self._example_features,
        )

    def save_checkpoint(self):
        """Params AND optimizer state (``opt/``-prefixed, mirroring
        spmd_trainer) — an elastic restore must resume the Adam/momentum
        trajectory, not restart it (reference PS slot persistence,
        go/pkg/ps/checkpoint.go:98-133).

        The device->host gather is synchronous (the next step's buffer
        donation invalidates the old arrays), but the disk write runs on
        a single background thread so the train loop only ever pays
        transfer time, not serialization+IO.  ``flush_checkpoints``
        joins pending writes (called at train end).

        ZeRO-1 state is checkpointed through its unpadding view
        (``_opt_state_on_host``): the file always holds original-shape
        leaves, so checkpoints are byte-portable between ``--zero1``
        on and off, and the on-device all-gather makes the host
        transfer safe in multi-controller worlds (raw ``to_numpy`` on
        a sharded leaf would hit non-addressable shards)."""
        with self.timing.timeit("checkpoint_save"):
            payload = dict(self.export_parameters())
            opt_named, _ = flatten_with_names(self._opt_state_on_host())
            payload.update({"opt/" + k: v for k, v in opt_named.items()})
            if self._ckpt_executor is None:
                from concurrent.futures import ThreadPoolExecutor

                self._ckpt_executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ckpt-write"
                )
            # Join the previous write first: bounds outstanding host
            # copies to one and guarantees its error (disk full, NFS)
            # surfaces HERE — raising out of train_minibatch so the
            # task fails visibly, exactly like the old synchronous save.
            self._surface_checkpoint_errors(wait=True)
            self._ckpt_future = self._ckpt_executor.submit(
                self._checkpoint_saver.save, self._version, dense=payload
            )
        logger.info("checkpoint at version %d queued for write",
                    self._version)

    def export_servable_now(self):
        """Continuous-export hook body (``--export_steps`` cadence):
        snapshot params on the caller (the next step's buffer donation
        invalidates device arrays, exactly the checkpoint constraint),
        then write the versioned servable on the same single background
        writer thread checkpoints use — the train loop pays host-gather
        time only, never npz serialization + fsync + rename.  The first
        export additionally traces/serializes the StableHLO program
        (ContinuousExporter caches it; steady state is weights-only).
        Errors surface on the NEXT cadence event, like checkpoint
        write errors."""
        bundle = self.serving_bundle()
        if bundle is None or self._exporter is None:
            return
        with self.timing.timeit("servable_export"):
            infer_fn, params, example = bundle
            version = self._version
            if self._ckpt_executor is None:
                from concurrent.futures import ThreadPoolExecutor

                self._ckpt_executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ckpt-write"
                )
            self._surface_export_errors(wait=True)
            tracing.event("worker.servable_export", version=version)
            self._export_future = self._ckpt_executor.submit(
                self._exporter.export, version, infer_fn, params,
                example,
            )
        self.timing.bump("servable_exports")

    def _surface_checkpoint_errors(self, wait):
        future = getattr(self, "_ckpt_future", None)
        if future is None:
            return
        if wait or future.done():
            self._ckpt_future = None
            try:
                future.result()
            except Exception as e:  # noqa: BLE001 — IO errors
                raise RuntimeError(
                    "async checkpoint write failed: %s" % (e,)
                ) from e

    def _surface_export_errors(self, wait):
        future = self._export_future
        if future is None:
            return
        if wait or future.done():
            self._export_future = None
            try:
                future.result()
            except Exception as e:  # noqa: BLE001 — IO / trace errors
                raise RuntimeError(
                    "async servable export failed: %s" % (e,)
                ) from e

    def flush_checkpoints(self):
        """Join pending checkpoint writes AND retire the writer thread
        (train end / before export).  Shutting the executor down here —
        not just joining the future — is the owner's stop path (EL007):
        a lazily re-created pool costs nothing, but a leaked one keeps
        its thread alive past the trainer and can hang worker exit.
        The next async save simply recreates it."""
        try:
            self._surface_checkpoint_errors(wait=True)
            self._surface_export_errors(wait=True)
        finally:
            # Retire the pool even when the surfaced write error
            # raises — the failure path must not leak the thread.
            if self._ckpt_executor is not None:
                self._ckpt_executor.shutdown(wait=True)
                self._ckpt_executor = None

    def init_from_checkpoint(self):
        if self._checkpoint_saver is None:
            return False
        self.flush_checkpoints()
        try:
            dense, _, version = self._checkpoint_saver.load()
        except FileNotFoundError:
            return False
        from elasticdl_tpu.utils.pytree import unflatten_from_names

        params_named = {
            k: v for k, v in dense.items() if not k.startswith("opt/")
        }
        opt_named = {
            k[len("opt/"):]: v for k, v in dense.items()
            if k.startswith("opt/")
        }
        self._params = unflatten_from_names(
            to_numpy(self._params), params_named
        )
        fresh_opt = True
        if opt_named:
            # Checkpoints hold ORIGINAL leaf shapes; restore against an
            # original-shape skeleton (a flat ZeRO-1 live state would
            # reject every leaf on shape) — rebuild() re-flattens and
            # re-shards below.
            template = (
                self._spec.optimizer.init(to_numpy(self._params))
                if self._opt_is_flat
                else to_numpy(self._opt_state)
            )
            try:
                self._opt_state = unflatten_from_names(
                    template, opt_named
                )
                self._opt_is_flat = False
                fresh_opt = False
            except (KeyError, ValueError) as e:
                # Optimizer changed since the checkpoint (e.g. Adam ->
                # momentum): params are still good, trajectory is not.
                logger.warning(
                    "checkpoint optimizer state incompatible (%s); "
                    "re-initializing optimizer", e,
                )
        if fresh_opt:  # pre-opt-state checkpoint or structure mismatch
            self._opt_state = self._spec.optimizer.init(self._params)
            self._opt_is_flat = False
        if self._mesh is not None:
            self.rebuild(self._mesh)
        self._version = version
        logger.info("restored checkpoint version %d", version)
        return True
