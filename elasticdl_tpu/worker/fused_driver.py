"""Fused-step training driver — the worker hot loop's windowed path.

The per-step loop pays three host costs on every minibatch: a device
sync (``float(loss)``), padding/reshape host work on the critical path,
and one ``report_batch_done`` RPC.  This driver amortizes all three
over a window of K steps while preserving elastic semantics *by
construction*:

 - **Multi-step dispatch**: K prefetched minibatches are stacked on the
   leading axis and run as ONE XLA program
   (``trainer.build_fused_window`` — a ``lax.scan`` of the raw step),
   so host dispatch latency amortizes over K optimizer steps.
 - **Device double-buffer**: batch padding/reshape/globalize runs in
   the ``prefetch_batches`` producer stage (``trainer.prepare_batch``),
   and the NEXT window is stacked and ``device_put`` while the current
   window's program is still executing — host feed and host→device
   transfer overlap the running step.
 - **Async loss cadence**: losses stay device-resident in a
   ``LossRing``; the only host syncs are one fetch per log cadence,
   one task-final fence (so a task is reported complete only after its
   last window verifiably finished), and one fence on preemption.

Composes with ``--zero1`` (ZeRO-1 weight-update sharding): the fused
window's opt-state carry is then the flat sharded form — each chained
step hands 1/N of the optimizer state to the next instead of a full
replicated copy — and window dispatches count their reduce-scatter /
all-gather payloads into ``Timing.summary()['zero1']``
(docs/training_pipeline.md has the carry-size math).

Elasticity is preserved because the window is **clamped** to the
distance to the next report/version/checkpoint/log/elastic-check
boundary (``_window_limit``) and to the task's remaining batches (the
stream simply ends), so every cadence event lands on exactly the same
step numbers as the per-step loop.  Preemption is observed between
windows: the in-flight window is fenced and its record counts flushed
(one coalesced ``report_batch_done`` RPC per window, mandatory flush
before the requeue), and batches collected but never dispatched are the
*unconsumed remainder* — they were never counted, so the master's shard
accounting is unchanged when the task is handed back.

Trainers opt in by implementing the window API
(``prepare_batch`` / ``stage_window`` / ``train_window`` /
``max_window`` / ``steps_to_boundary``); ``ParameterServerTrainer``
keeps ``max_window = 1`` (its overlap lives in the async push pipeline,
see docs/ps_pipeline.md), which routes it through the classic per-step
loop unchanged.

Failure semantics: a fused window has no per-minibatch retry — a
dispatch error fails the whole task and the master's task-retry
machinery takes over (the per-step loop keeps its retry budget; the
worker selects it for ``--fused_steps 1`` and for every trainer whose
``max_window`` is 1).
"""

import time
from collections import namedtuple

import numpy as np

from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# One host-prepared minibatch: padded/reshaped (and, multi-controller,
# globalized) leaves plus the pre-pad record count the shard protocol
# reports.  ``weights`` is the loss mask (None for trainers that mask
# internally, e.g. the PS path pads inside train_minibatch).
PreparedBatch = namedtuple(
    "PreparedBatch", ["features", "labels", "weights", "count"]
)

# A window of ``size`` prepared batches stacked on the leading axis
# (size 1 keeps the unstacked leaves), possibly already device-resident.
StagedWindow = namedtuple(
    "StagedWindow", ["size", "features", "labels", "weights"]
)


class LossRing:
    """Holder for the newest window's device-resident losses.

    ``push`` never touches the device; ``fetch_last`` performs the ONE
    host sync (a value fetch — on this session's TPU relay
    ``block_until_ready`` does not fence, so the fetch is the fence)
    and clears the slot.  Because windows chain through the params
    pytree, fetching the newest window's losses proves every earlier
    step completed too — which is why only the newest entry is kept:
    older device arrays would be pinned for nothing.
    """

    def __init__(self):
        self._latest = None

    def __len__(self):
        return 0 if self._latest is None else 1

    def push(self, step, losses):
        """``losses``: device scalar (window of 1) or [K] device array;
        ``step`` is the global step number of the window's LAST step."""
        self._latest = (step, losses)

    def fetch_last(self):
        """Fetch the newest window's losses (one device sync), clear
        the slot, and return ``(step, last_loss_float)`` — or None when
        nothing is pending."""
        if self._latest is None:
            return None
        step, losses = self._latest
        values = np.asarray(losses).reshape(-1)  # the device sync
        self._latest = None
        return step, float(values[-1])


class FusedStepDriver:
    """Windowed training loop over one task's prepared-batch stream."""

    def __init__(
        self,
        trainer,
        shard_service,
        timing,
        fused_steps=1,
        device_prefetch=2,
        log_loss_steps=100,
        elastic=None,
        stop_check=None,
        callbacks=(),
        prepare=None,
        step_throttle_secs=0.0,
    ):
        """``prepare``: optional item -> PreparedBatch hook applied
        INSIDE the loop, after each window's elastic epoch check — the
        elastic path uses it so a world re-form (which can change batch
        geometry via an accum resize) never sees batches prepared under
        the old world.  None means the stream already yields
        PreparedBatch (the prefetch producer prepared them)."""
        self._trainer = trainer
        self._shard = shard_service
        self._timing = timing
        self._prepare = prepare
        self._fused_steps = max(1, int(fused_steps))
        # > 0: stage (stack + device_put) the next window while the
        # current one executes — the device double-buffer.  0: stage at
        # dispatch time (transfer lands on the critical path).  Staging
        # ahead requires already-prepared items (``prepare is None`` =
        # the producer prepared them); with a driver-side prepare hook
        # — the elastic case — the stage is ALWAYS deferred past the
        # window's epoch check: a world re-form clears XLA backends,
        # which would invalidate anything staged ahead of it.
        self._stage_ahead = device_prefetch > 0 and prepare is None
        self._log_loss_steps = log_loss_steps
        self._elastic = elastic
        self._stop_check = stop_check
        self._callbacks = callbacks
        # Drill knob (worker.step_throttle_secs): deliberate per-step
        # slowdown so churn drills can stage a straggler on the FUSED
        # path too — without this the env-armed throttle would be a
        # silent no-op for any fused-config worker.
        self._step_throttle = float(step_throttle_secs or 0.0)
        self.loss_ring = LossRing()

    @property
    def effective_window(self):
        """Configured window clamped to the trainer's structural cap
        (1 for the PS path; 1 for multi-controller collectives, whose
        batches are already committed global arrays)."""
        cap = getattr(self._trainer, "max_window", None)
        if cap:
            return min(self._fused_steps, cap)
        return self._fused_steps

    @staticmethod
    def _dist(steps_done, cadence):
        """Steps until ``steps_done`` next lands on a cadence multiple."""
        return cadence - (steps_done % cadence)

    def _window_limit(self, steps_done):
        """Clamp the next window so every cadence event (loss log,
        version report, checkpoint, elastic epoch check) fires at the
        same step number the per-step loop would fire it at."""
        w = self.effective_window
        if self._log_loss_steps:
            w = min(w, self._dist(steps_done, self._log_loss_steps))
        boundary_fn = getattr(self._trainer, "steps_to_boundary", None)
        boundary = boundary_fn() if boundary_fn is not None else None
        if boundary:
            w = min(w, boundary)
        if self._elastic is not None:
            # Epoch checks run at window granularity (one step_check
            # per window, counted as the window's steps) — clamping
            # here bounds how far past the per-step cadence a check can
            # drift to less than one window; the check may fire up to
            # window-1 steps EARLIER than the per-step loop's, which is
            # safe for a poll (init_world_if_needed only re-forms when
            # the epoch actually changed).  Exact step-number parity is
            # only guaranteed for the report/checkpoint/log boundaries
            # above.
            check_fn = getattr(self._elastic, "steps_to_check", None)
            check = check_fn() if check_fn is not None else None
            if check:
                w = min(w, check)
        return max(1, w)

    @staticmethod
    def _collect(batch_iter, k):
        """Pull up to k prepared batches; fewer means the task's stream
        ended (the window clamps to the task's remaining batches)."""
        out = []
        for _ in range(k):
            item = next(batch_iter, None)
            if item is None:
                break
            out.append(item)
        return out

    def _fence(self):
        """One blocking loss fetch — the sync half of the
        dispatch-vs-sync timing split (see Timing.sync_fraction)."""
        with self._timing.timeit("loss_sync"):
            return self.loss_ring.fetch_last()

    def _stage(self, batches):
        """Stage ahead (the device double-buffer) when enabled; None
        defers staging to dispatch time — after the window's elastic
        epoch check, so a world re-form never strands staged device
        arrays on a cleared backend."""
        if not batches or not self._stage_ahead:
            return None
        with self._timing.timeit("host_prep"):
            return self._trainer.stage_window(batches, to_device=True)

    def _dispatch(self, cur, staged):
        """Dispatch one window; ``staged`` is the ahead-staged form (or
        None when staging was deferred past the epoch check)."""
        trainer = self._trainer
        if staged is not None:
            return trainer.train_window(staged)
        cap = getattr(trainer, "max_window", None)
        if cap and len(cur) > cap:
            # An epoch re-form between collect and dispatch shrank the
            # structural window cap (e.g. the world grew to
            # multi-controller): dispatch the already-collected batches
            # singly — correctness over overlap for this one window.
            losses = []
            version = None
            for batch in cur:
                staged_one = trainer.stage_window([batch], to_device=True)
                loss, version = trainer.train_window(staged_one)
                losses.append(loss)
            return losses, version
        return trainer.train_window(
            trainer.stage_window(cur, to_device=True)
        )

    def run_task(self, batch_iter, steps_done=0):
        """Drive one task's stream through fused windows.

        ``batch_iter`` yields PreparedBatch (prep already ran in the
        prefetch producer).  Returns ``(steps_run, preempted)``; the
        caller raises its preemption exception and requeues the task.
        Dispatch errors propagate to the caller's task-failure path.
        """
        trainer, timing = self._trainer, self._timing
        start = steps_done
        with timing.timeit("data_wait"):
            cur = self._collect(batch_iter,
                                self._window_limit(steps_done))
        staged = self._stage(cur)
        # Step-time anatomy (docs/observability.md): each loop pass
        # below is decomposed into data_wait (producer starvation) /
        # host_prep (stack + device_put) / window_dispatch (XLA
        # enqueue) / loss_sync (device fence) / progress_rpc (master
        # report), each feeding a per-phase histogram via Timing; the
        # whole pass's wall time over its step count is the honest
        # per-step step time (windowed dispatch means individual steps
        # inside one program are not separately observable).
        t_prev = time.perf_counter()
        while cur:
            if self._elastic is not None:
                # One epoch check per window, counted as len(cur) steps
                # so the check cadence matches the per-step loop's.
                self._elastic.step_check(len(cur))
            for callback in self._callbacks:
                if hasattr(callback, "on_train_batch_begin"):
                    for _ in cur:  # once per step, as the old loop did
                        callback.on_train_batch_begin(trainer)
            if self._prepare is not None:
                # Post-epoch-check prep (elastic path): the batches are
                # prepared against the CURRENT world's geometry.
                cur = [self._prepare(item) for item in cur]
            with timing.timeit("window_dispatch"):
                losses, version = self._dispatch(cur, staged)
            if self._step_throttle:
                time.sleep(self._step_throttle * len(cur))
            steps_done += len(cur)
            timing.bump("fused_windows")
            timing.bump("fused_steps_run", len(cur))
            # Collect + stage the NEXT window while the current one is
            # still executing on device: host feed and host→device
            # transfer overlap the running step.
            with timing.timeit("data_wait"):
                nxt = self._collect(batch_iter,
                                    self._window_limit(steps_done))
            staged = self._stage(nxt)
            self.loss_ring.push(steps_done, losses)
            fetched = None
            if not nxt:
                # Task-final fence BEFORE the final report: the last
                # window must verifiably complete before the shard
                # protocol can auto-complete the task (same strictness
                # the per-step loop had via its per-step sync).
                fetched = self._fence()
            # Coalesced progress accounting: one report_batch_done RPC
            # per fused window (counts buffered per batch, flushed at
            # the window boundary — and, structurally, at task
            # boundaries inside DataShardService).
            with timing.timeit("progress_rpc"):
                for batch in cur:
                    self._shard.report_batch_done(batch.count,
                                                  defer=True)
                self._shard.flush_batch_done()
            # One bulk observation per window: this pass's wall time
            # spread over its steps — the step-time distribution the
            # master aggregates per job (and judges stragglers on).
            t_now = time.perf_counter()
            timing.observe("step_time",
                           (t_now - t_prev) / len(cur), n=len(cur))
            t_prev = t_now
            if (
                self._log_loss_steps
                and steps_done % self._log_loss_steps == 0
            ):
                if fetched is None:
                    fetched = self._fence()
                if fetched is not None:
                    logger.info(
                        "step %d loss %.6f (version %d)",
                        fetched[0], fetched[1], version,
                    )
            if self._stop_check is not None and self._stop_check():
                # Graceful preemption between windows: fence the
                # in-flight window, flush the (already reported) window
                # counts, and hand back.  ``nxt`` was collected but
                # never dispatched — the unconsumed remainder, never
                # counted, requeued with the task.
                self._fence()
                self._shard.flush_batch_done()
                tracing.event("worker.preempt_flush",
                              steps_run=steps_done - start,
                              undispatched=len(nxt))
                return steps_done - start, True
            cur = nxt
        return steps_done - start, False
