"""Trainer interface (parity: elasticdl/python/worker/trainer.py:17-56)."""

import abc


class Trainer(abc.ABC):
    @abc.abstractmethod
    def train_minibatch(self, features, labels):
        """Run one training step; returns (loss: float, version: int)."""

    @abc.abstractmethod
    def evaluate_minibatch(self, features, labels):
        """Forward pass; returns (outputs ndarray, labels ndarray)."""

    @abc.abstractmethod
    def predict_minibatch(self, features):
        """Forward pass; returns outputs ndarray."""

    def init_from_checkpoint(self):
        return False

    def export_parameters(self):
        """Return {name: ndarray} of the current model parameters."""
        raise NotImplementedError
