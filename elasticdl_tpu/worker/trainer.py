"""Trainer interface (parity: elasticdl/python/worker/trainer.py:17-56)."""

import abc


class Trainer(abc.ABC):
    @abc.abstractmethod
    def train_minibatch(self, features, labels):
        """Run one training step; returns (loss, version: int).

        ``loss`` is a LAZY device scalar — no host sync happens here.
        Callers that need a float (cadence logging, benches) convert
        explicitly with ``float(loss)``; that fetch is the device
        fence.  Trainers may additionally implement the fused-window
        API (``prepare_batch`` / ``stage_window`` / ``train_window`` /
        ``max_window`` / ``steps_to_boundary``) to opt into multi-step
        dispatch (worker/fused_driver.py)."""

    @abc.abstractmethod
    def evaluate_minibatch(self, features, labels):
        """Forward pass; returns (outputs ndarray, labels ndarray)."""

    @abc.abstractmethod
    def predict_minibatch(self, features):
        """Forward pass; returns outputs ndarray."""

    def init_from_checkpoint(self):
        return False

    def export_parameters(self):
        """Return {name: ndarray} of the current model parameters."""
        raise NotImplementedError

    def serving_bundle(self):
        """Optional (inference_fn, params_pytree, example_input) triple
        for a standalone servable export (serving/export.py); None when
        the trainer can't provide one."""
        return None
