"""Worker task loop.

Parity with elasticdl/python/worker/worker.py:46-449: fetch task -> stream
records -> train/evaluate/predict minibatches; a failing minibatch retries
up to 64 times (reference DEFAULT_MAX_MINIBATCH_RETRY_NUM, worker.py:39);
evaluation results go to the master's evaluation service; the train-end
callback task runs model-export callbacks on exactly one worker.
"""

import os
import time

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import hist as hist_mod
from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.retry import RetryPolicy
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.data_shard_service import DataShardService
from elasticdl_tpu.worker.task_data_service import TaskDataService

logger = get_logger(__name__)

DEFAULT_MAX_MINIBATCH_RETRY_NUM = 64

# Container convention for "terminated by SIGTERM" — the worker manager
# classifies it as a preemption (relaunch), not a failure.
PREEMPTED_EXIT_CODE = 143


class PreemptedExit(Exception):
    """Raised inside the task loop when a graceful-preemption stop was
    requested (SIGTERM): unwind cleanly after the current minibatch."""


# Drill knob: "id:ms[,id:ms...]" — a deliberate per-step sleep for the
# NAMED worker ids only (bench_elastic's straggler leg throttles one
# member of a managed pool through the shared environment).
ENV_STEP_THROTTLE = "ELASTICDL_STEP_THROTTLE_SPEC"


def step_throttle_secs(worker_id, spec=None):
    """Seconds of deliberate per-step sleep for ``worker_id`` under
    the current ELASTICDL_STEP_THROTTLE_SPEC ("id:ms,..."), else 0.
    Malformed specs are ignored loudly — a drill typo must never
    change training behavior silently."""
    spec = (os.environ.get(ENV_STEP_THROTTLE, "")
            if spec is None else spec)
    for piece in spec.split(","):
        piece = piece.strip()
        if not piece:
            continue
        try:
            wid, ms = piece.split(":")
            if int(wid) == worker_id:
                throttle = float(ms) / 1e3
                logger.warning(
                    "worker %d DELIBERATELY throttled %.0f ms/step "
                    "(%s)", worker_id, float(ms), ENV_STEP_THROTTLE)
                return throttle
        except ValueError:
            logger.warning("ignoring bad %s piece %r",
                           ENV_STEP_THROTTLE, piece)
    return 0.0


class Worker:
    def __init__(
        self,
        master_client,
        data_reader,
        spec,
        trainer,
        batch_size,
        max_minibatch_retries=DEFAULT_MAX_MINIBATCH_RETRY_NUM,
        log_loss_steps=100,
        join_rendezvous=False,
        elastic_controller=None,
        fused_steps=1,
        device_prefetch=2,
        job_context_factory=None,
        initial_job_config=None,
    ):
        """``elastic_controller`` (ElasticCollectiveController): drives
        the multi-controller collective world from inside the managed
        task loop — epoch checks before minibatches (step-count
        cadence, SPMD-aligned across workers) and await-new-epoch on a
        failed collective.  None = single-process trainer (the
        historical managed path).

        ``fused_steps``: run up to K optimizer steps per device
        dispatch through the fused-step driver (worker/fused_driver.py)
        when the trainer supports windows; 1 (default) is exactly the
        classic per-step loop.  ``device_prefetch``: prepared-batch
        lookahead depth for the producer stage; > 0 also stages the
        next window's device transfer behind the running step, 0 keeps
        batch prep on the dispatch path.

        ``job_context_factory`` (multi-tenant pools, docs/scheduler.md):
        ``factory(job_config) -> (data_reader, spec, trainer)`` —
        called when the scheduler re-assigns this worker to a
        different job (the get_task handshake), so the worker rebuilds
        its data pipeline and per-job model state IN PLACE, without a
        process restart.  None = single-job worker (handshakes are
        adopted as an id only)."""
        self._mc = master_client
        self._spec = spec
        self._trainer = trainer
        self._batch_size = batch_size
        self._max_minibatch_retries = max_minibatch_retries
        self._log_loss_steps = log_loss_steps
        self._join_rendezvous = join_rendezvous
        self._elastic = elastic_controller
        self._fused_steps = max(1, int(fused_steps))
        self._device_prefetch = max(0, int(device_prefetch))
        self._shard_service = DataShardService(
            master_client, batch_size,
            # The WAIT poll must abort on graceful preemption — an idle
            # worker's grace window would otherwise expire inside it.
            stop_check=lambda: self._preempt_requested,
            # Live steps/s + health piggybacked on every progress RPC
            # (docs/observability.md): the master aggregates these into
            # its per-job telemetry surface.
            telemetry_fn=self._telemetry_snapshot,
        )
        self._data_service = TaskDataService(data_reader, spec.feed)
        self.timing = Timing(logger=logger)
        # One retry policy family (utils/retry.py): the minibatch loop
        # below keeps its structure (the elastic branch re-rendezvouses
        # instead of sleeping) but the backoff/budget bookkeeping and
        # the rpc_retry/rpc_gaveup counters are shared with every other
        # outage-riding client in the worker.
        self._minibatch_backoff = RetryPolicy(
            name="minibatch",
            max_attempts=max_minibatch_retries,
            deadline_secs=None,
            base_delay_secs=0.1,
            max_delay_secs=3.0,
            timing=self.timing,
        )
        retry_policy = getattr(master_client, "retry_policy", None)
        if retry_policy is not None and retry_policy.timing is None:
            # The MasterClient is built before the Worker owns a
            # Timing; bind it so master-RPC retries land in the same
            # reported counters.
            retry_policy.timing = self.timing
        self._steps = 0
        self._preempt_requested = False
        self.preempted = False
        # Multi-tenant re-assignment handshake state: the job this
        # worker's pipeline is currently built for, and the config key
        # it was built from (an identical config skips the rebuild —
        # e.g. the pool template already matches the assigned job).
        self._job_factory = job_context_factory
        self._job_id = getattr(master_client, "job_id", 0) or 0
        self._job_key = (
            self._job_config_key(initial_job_config)
            if initial_job_config else None
        )
        # Drill-only deliberate slowdown (straggler staging): the
        # ELASTICDL_STEP_THROTTLE_SPEC env names worker ids — every
        # pool worker inherits the same env, each applies only its
        # own entry, so a drill can throttle ONE member of a managed
        # pool without per-worker plumbing.
        self._step_throttle = step_throttle_secs(
            getattr(master_client, "worker_id", -1))
        # (monotonic mark, steps at mark) for the steps/s telemetry
        # interval; written and read only on the training thread (the
        # progress-RPC flush runs there).
        self._tele_mark = (None, 0)
        # Step-time histogram snapshot at the previous report — the
        # piggybacked delta is cur - prev, so the master's merge stays
        # an exact cumulative sum however reports interleave.  Same
        # single-thread discipline as _tele_mark.
        self._tele_hist_prev = None

    def _telemetry_snapshot(self):
        """Telemetry dict for the next progress RPC: worker-local
        steps/s over the interval since the previous report,
        blocked-on-device fraction, PS push-pipeline depth, the mean
        fused-window size, and the sparse step-time histogram delta
        (docs/observability.md — the master's per-job p50/p99 and the
        straggler detector derive from it)."""
        now = time.monotonic()
        mark_t, mark_steps = self._tele_mark
        self._tele_mark = (now, self._steps)
        out = {"steps_done": self._steps}
        step_snap = self.timing.hist_snapshot("step_time")
        if step_snap is not None:
            d = hist_mod.delta(step_snap, self._tele_hist_prev)
            self._tele_hist_prev = step_snap
            if d["count"]:
                out["hist_delta"] = hist_mod.encode_deltas(
                    {"step_time": d})
        if mark_t is not None and now > mark_t and (
            self._steps > mark_steps
        ):
            out["steps_per_sec"] = (
                (self._steps - mark_steps) / (now - mark_t)
            )
        staleness = getattr(self._trainer, "push_staleness", None)
        if staleness is not None:
            out["push_staleness"] = float(staleness())
        counters = self.timing.counters()
        windows = counters.get("fused_windows", 0)
        if windows:
            out["window_size"] = (
                counters.get("fused_steps_run", 0) / windows
            )
            # Only meaningful on the fused path: the per-step loop
            # records loss_sync but never window_dispatch, so the
            # ratio there would read 1.0 ("fully device-stalled") on
            # every default-config worker regardless of overlap.
            sync = self.timing.sync_fraction("window_dispatch",
                                             "loss_sync")
            if sync is not None:
                out["sync_fraction"] = sync
        return out

    # Handshake-config fields that change what the worker pipeline is
    # built from.  Used ONLY for the first-assignment fast path (pool
    # template already matches the job): cross-job moves always
    # rebuild, identical config or not — tenant isolation.
    _JOB_KEY_FIELDS = (
        "model_zoo", "model_params", "data_origin", "batch_size",
        "num_minibatches_per_task", "seed", "checkpoint_dir",
        "distribution_strategy",
    )

    @classmethod
    def _job_config_key(cls, cfg):
        return tuple(
            (field, cfg.get(field)) for field in cls._JOB_KEY_FIELDS
        )

    def _maybe_switch_job(self):
        """The re-assignment handshake (docs/scheduler.md): when the
        master's get_task response moved this worker to a different
        job, rebuild the data pipeline / per-job trainer state IN
        PLACE — the process survives, which is the whole point of the
        shared pool.  A pipeline-identical config (the pool template
        matching the assigned job) skips the rebuild."""
        new_job = getattr(self._mc, "job_id", 0) or 0
        if not new_job or new_job == self._job_id:
            return
        prev_job, self._job_id = self._job_id, new_job
        cfg = getattr(self._mc, "job_config", None)
        if self._job_factory is None or not cfg:
            logger.info(
                "adopted job %d (no context factory; pipeline kept)",
                new_job,
            )
            return
        key = self._job_config_key(cfg)
        if prev_job == 0 and key == self._job_key:
            # Fast path for the FIRST assignment only: the eagerly
            # built pool-template pipeline already matches this job,
            # and no other tenant's state has touched it.  A CROSS-JOB
            # move always rebuilds even on an identical config —
            # reusing the trainer would carry the previous tenant's
            # trained parameters into the new job.
            logger.info(
                "registered into job %s (id %d): pool template "
                "matches, rebuild skipped", cfg.get("job"), new_job,
            )
            return
        # Note: collective pool workers never reach here — their
        # elastic controller is bound to ONE trainer, so worker/main
        # wires the factory for local-strategy pools only and
        # collective workers adopt re-assignments as an id (the
        # factory-None path above).  Cross-job collective moves would
        # add LOOP_END(old job)/leave_world before the rebuild and
        # LOOP_START/rejoin_world after it.
        with tracing.span("worker.job_switch", job=new_job,
                          prev_job=prev_job,
                          job_name=str(cfg.get("job"))):
            old_trainer = self._trainer
            if old_trainer is not None and hasattr(old_trainer,
                                                  "close"):
                try:
                    old_trainer.close()
                except Exception as e:  # noqa: BLE001 — best effort:
                    # the old job's trainer must not block the new one
                    logger.warning("old trainer close failed: %s", e)
            reader, spec, trainer = self._job_factory(cfg)
            self._spec = spec
            self._trainer = trainer
            self._data_service = TaskDataService(reader, spec.feed)
            batch_size = int(cfg.get("batch_size") or self._batch_size)
            self._batch_size = batch_size
            self._shard_service.set_batch_size(batch_size)
            self._job_key = key
        logger.info(
            "switched to job %s (id %d): data=%s model=%s",
            cfg.get("job"), new_job, cfg.get("data_origin"),
            cfg.get("model_zoo"),
        )

    def request_stop(self):
        """Graceful-preemption hook (SIGTERM handler, worker main):
        finish the in-flight minibatch, checkpoint if configured,
        report the unfinished task back, exit with PREEMPTED_EXIT_CODE
        so the manager relaunches.  Preemptible TPU VMs give ~30 s of
        notice — enough to save the optimizer trajectory instead of
        replaying from the last periodic checkpoint (reference analog:
        pod eviction grace)."""
        self._preempt_requested = True

    # -- task handlers ------------------------------------------------------

    def _process_minibatch(self, features, labels):
        err = None
        for callback in self._spec.callbacks:
            if hasattr(callback, "on_train_batch_begin"):
                callback.on_train_batch_begin(self._trainer)
        for attempt in range(self._max_minibatch_retries):
            try:
                if self._elastic is not None:
                    # Step-count cadence: every member of the world
                    # checks at the same collective index, so nobody
                    # leaves an epoch while a peer is blocked inside
                    # one of its collectives.
                    self._elastic.step_check()
                loss, version = self._trainer.train_minibatch(
                    features, labels
                )
                if (
                    self._elastic is not None
                    and self._elastic.world_size > 1
                ):
                    # Multi-controller worlds keep the per-step sync:
                    # an in-band collective failure must surface ON the
                    # failing minibatch, inside THIS retry scope, so
                    # the await-new-epoch recovery below retries the
                    # right batch before its records are reported done.
                    # (Cross-process collectives serialize on the wire
                    # anyway — the lazy-loss win lives on the
                    # single-controller hot paths.)
                    float(loss)
                self._steps += 1
                if self._steps % self._log_loss_steps == 0:
                    # train_minibatch returns a LAZY device loss; this
                    # float() is the only per-cadence host sync.
                    with self.timing.timeit("loss_sync"):
                        loss_value = float(loss)
                    logger.info(
                        "step %d loss %.6f (version %d)",
                        self._steps, loss_value, version,
                    )
                if self._step_throttle:
                    # Drill knob (step_throttle_secs): a DELIBERATE
                    # per-step slowdown so churn drills can stage a
                    # straggler and gate the detector on it.
                    time.sleep(self._step_throttle)
                return loss
            except Exception as e:  # noqa: BLE001 — retry then surface
                err = e
                logger.warning(
                    "minibatch failed (attempt %d): %s", attempt + 1, e
                )
                if (
                    self._elastic is not None
                    and self._elastic.world_size > 1
                ):
                    # In-band collective failure: the world is dead
                    # until the master commits a new epoch (reference
                    # allreduce_trainer.py:77-91) — wait for it; if
                    # none arrives (transient error, membership
                    # unchanged) force a re-init of the current world.
                    # Each of these costs up to a minute, so the
                    # elastic path gets a SHORT retry budget — after
                    # that the task fails and the task-retry machinery
                    # takes over.
                    if attempt + 1 >= 3:
                        break
                    if not self._elastic.await_new_epoch():
                        self._elastic.init_world_if_needed(force=True)
                    continue
                # Jittered exponential backoff (shared policy) so the
                # retry budget rides out transient outages (a PS shard
                # relaunching takes seconds; 64 instant retries would
                # burn out in <1s).
                self._minibatch_backoff.pause(min(attempt, 5))
        raise RuntimeError(
            "minibatch failed after %d retries" % self._max_minibatch_retries
        ) from err

    def _windowed_driver(self):
        """The fused-step driver when it would actually fuse (> 1 step
        per dispatch); None selects the classic per-step loop — which
        stays the path for ``--fused_steps 1``, the PS trainer
        (max_window 1) and multi-controller collectives."""
        if self._fused_steps <= 1 or not hasattr(
            self._trainer, "train_window"
        ):
            return None
        from elasticdl_tpu.worker.fused_driver import FusedStepDriver

        driver = FusedStepDriver(
            self._trainer, self._shard_service, self.timing,
            fused_steps=self._fused_steps,
            device_prefetch=self._device_prefetch,
            log_loss_steps=self._log_loss_steps,
            elastic=self._elastic,
            stop_check=lambda: self._preempt_requested,
            callbacks=self._spec.callbacks,
            step_throttle_secs=self._step_throttle,
            # Prep placement: producer thread when no elastic
            # controller (overlap), inside the driver AFTER the epoch
            # check otherwise — a world re-form can change batch
            # geometry (accum resize), and batches prepared ahead
            # under the old world must never be dispatched after it.
            prepare=(
                None if self._producer_prepares()
                else lambda item: self._trainer.prepare_batch(*item)
            ),
        )
        return driver if driver.effective_window > 1 else None

    def _producer_prepares(self):
        return self._device_prefetch > 0 and self._elastic is None

    def _run_task_windowed(self, task, driver):
        """Fused hot loop: batch prep in the prefetch producer, K steps
        per dispatch, device double-buffer, coalesced progress RPCs,
        loss fetched at cadence (docs/training_pipeline.md)."""
        from elasticdl_tpu.data.parallel_reader import prefetch_batches

        prepare = None
        if self._producer_prepares():
            prepare = lambda item: self._trainer.prepare_batch(*item)
        # else: the driver preps each window itself, after its elastic
        # epoch check (or at dispatch with --device_prefetch 0) — the
        # stream hands raw (features, labels, count) items through.
        batches = prefetch_batches(
            self._data_service.batch_stream(task, self._batch_size),
            depth=max(2, self._device_prefetch),
            prepare=prepare,
        )
        ran, preempted = driver.run_task(
            batches, steps_done=self._steps
        )
        self._steps += ran
        if preempted or self._preempt_requested:
            raise PreemptedExit()

    def _train_task(self, task):
        from elasticdl_tpu.data.parallel_reader import prefetch_batches

        driver = self._windowed_driver()
        # PS trainers can start the NEXT batch's embedding pulls while
        # the current device step runs; the one-batch lookahead below
        # feeds that prefetcher (it composes with prefetch_batches,
        # which overlaps read/decode/feed one stage earlier).
        prefetch_embeddings = getattr(
            self._trainer, "prefetch_embeddings", None
        )
        with self.timing.timeit("task_process"):
            try:
                if driver is not None:
                    self._run_task_windowed(task, driver)
                    return
                # Prefetch so host-side read/decode/feed overlaps the
                # device step (the input-pipeline half of keeping the
                # MXU busy); producer errors re-raise here where the
                # task-failure reporting lives.
                batches = prefetch_batches(
                    self._data_service.batch_stream(
                        task, self._batch_size
                    ),
                    depth=2,
                )
                pending = next(batches, None)
                t_prev = time.perf_counter()
                while pending is not None:
                    features, labels, count = pending
                    pending = next(batches, None)
                    if pending is not None and prefetch_embeddings:
                        prefetch_embeddings(pending[0])
                    loss = self._process_minibatch(features, labels)
                    # Per-step wall time into the step-time histogram
                    # (the fused path observes per window); feeds the
                    # master's per-job p50/p99 via the telemetry
                    # piggyback's hist delta.
                    t_now = time.perf_counter()
                    self.timing.observe("step_time", t_now - t_prev)
                    t_prev = t_now
                    if pending is None:
                        # Task-final fence: the last report below can
                        # auto-complete the task at the master, so the
                        # last (lazy) step must verifiably finish
                        # first — the completion guarantee the loop
                        # used to get for free from per-step
                        # float(loss); steps chain through params, so
                        # fencing the last one proves them all.
                        with self.timing.timeit("loss_sync"):
                            float(loss)
                    self._shard_service.report_batch_done(count)
                    if self._preempt_requested:
                        raise PreemptedExit()
            except PreemptedExit:
                # Give the unfinished remainder back WITHOUT consuming
                # a retry (the task isn't at fault — frequent evictions
                # must not permanently fail it), and unwind to run(),
                # which checkpoints and exits.
                self._shard_service.report_task_failed(
                    task, "worker preempted (graceful)", requeue=True)
                raise
            except Exception as e:  # noqa: BLE001
                # Report the failure so the master can retry the task on
                # another worker; keep this worker alive for the next task.
                logger.error("training task %d failed: %s", task.id, e)
                self._shard_service.report_task_failed(task, str(e))

    def _evaluate_task(self, task):
        try:
            for features, labels, _ in self._data_service.batch_stream(
                task, self._batch_size
            ):
                outputs, labels = self._trainer.evaluate_minibatch(
                    features, labels
                )
                self._mc.report_evaluation_metrics(
                    outputs, labels, model_version=task.model_version,
                )
            self._shard_service.report_task_done(task)
        except Exception as e:  # noqa: BLE001
            self._shard_service.report_task_failed(task, str(e))
            raise

    def _predict_task(self, task):
        processor = self._spec.prediction_outputs_processor
        try:
            for features, _labels, _ in self._data_service.batch_stream(
                task, self._batch_size
            ):
                outputs = self._trainer.predict_minibatch(features)
                if processor is not None:
                    processor.process(outputs, self._mc.worker_id)
            if processor is not None and hasattr(processor, "flush"):
                processor.flush()
            self._shard_service.report_task_done(task)
        except Exception as e:  # noqa: BLE001
            self._shard_service.report_task_failed(task, str(e))
            raise

    def _train_end_task(self, task):
        try:
            # Join any in-flight async checkpoint write before export
            # callbacks read the checkpoint directory.
            if hasattr(self._trainer, "flush_checkpoints"):
                self._trainer.flush_checkpoints()
            for callback in self._spec.callbacks:
                if hasattr(callback, "on_train_end"):
                    callback.on_train_end(self._trainer)
            self._shard_service.report_task_done(task)
        except Exception as e:  # noqa: BLE001
            self._shard_service.report_task_failed(task, str(e))
            raise

    # -- main loop ----------------------------------------------------------

    def _fetch_task_elastic(self):
        """Fetch without idling INSIDE the collective world.

        A worker holding no task must not stall its peers' collectives
        (they step in lockstep) nor keep a heartbeat against an epoch
        service the master will reap — so on WAIT it LEAVES the world
        (LOOP_END + drop the coordination client; the survivors
        re-form without it), polls for work from outside, and rejoins
        (LOOP_START + re-init) when a task shows up."""
        from elasticdl_tpu.worker.data_shard_service import WAIT

        task = self._shard_service.fetch_task(return_wait=True)
        if task is not WAIT:
            return task
        logger.info("no task available; leaving the collective world")
        self._elastic.leave_world()
        self._mc.report_train_loop_status(pb.LOOP_END)
        while task is WAIT:
            if self._preempt_requested:
                raise PreemptedExit()  # honor SIGTERM while idle too
            time.sleep(0.5)
            task = self._shard_service.fetch_task(return_wait=True)
        if task is not None:
            logger.info("task available; rejoining the collective world")
            self._mc.report_train_loop_status(pb.LOOP_START)
            self._elastic.rejoin_world()
        return task

    def _run_one_task(self, task):
        # One span per task: everything underneath — minibatch RPC
        # client spans, outage-riding retry events, the master-side
        # server spans and task.completed breadcrumbs — shares this
        # trace, so a churn drill reads as one causal timeline.
        with tracing.span("worker.task", task=task.id,
                          type=int(task.type)):
            if task.type == pb.TRAINING:
                self._train_task(task)
            elif task.type == pb.EVALUATION:
                self._evaluate_task(task)
            elif task.type == pb.PREDICTION:
                self._predict_task(task)
            elif task.type == pb.TRAIN_END_CALLBACK:
                self._train_end_task(task)
            else:
                logger.warning("unknown task type %s", task.type)
                self._shard_service.report_task_done(task)

    def run(self):
        # Root span for the whole run: the worker's single trace id —
        # task spans nest under it, so even fetch-loop retries during
        # a master outage land in the same trace.
        with tracing.span("worker.run", worker=self._mc.worker_id):
            self._run_traced()

    def _run_traced(self):
        if self._join_rendezvous:
            self._mc.report_train_loop_status(pb.LOOP_START)
        try:
            while True:
                if self._preempt_requested:
                    raise PreemptedExit()
                if self._elastic is not None:
                    task = self._fetch_task_elastic()
                else:
                    task = self._shard_service.fetch_task()
                # The get_task that delivered this task may have been
                # the scheduler's re-assignment handshake: rebuild the
                # pipeline for the new job BEFORE processing the task.
                self._maybe_switch_job()
                if task is None:
                    if self._preempt_requested:
                        # The fetch aborted because of the SIGTERM, not
                        # because the job finished — checkpoint first.
                        raise PreemptedExit()
                    break
                self._run_one_task(task)
        except PreemptedExit:
            self.preempted = True
            logger.warning(
                "graceful preemption: saving checkpoint and exiting")
            if getattr(self._trainer, "_checkpoint_saver", None):
                try:
                    self._trainer.save_checkpoint()
                    self._trainer.flush_checkpoints()
                except Exception as e:  # noqa: BLE001 — best effort
                    # under a kill deadline: a failed save must not
                    # mask the preemption exit path
                    logger.error("preemption checkpoint failed: %s", e)
        finally:
            if hasattr(self._trainer, "close"):
                # Drain any in-flight async gradient pushes and stop
                # the trainer's background threads before reporting.
                try:
                    self._trainer.close()
                except Exception as e:  # noqa: BLE001 — best effort
                    logger.warning("trainer close failed: %s", e)
            if self._join_rendezvous:
                self._mc.report_train_loop_status(pb.LOOP_END)
            self.timing.report()
