"""Client-side dynamic sharding protocol.

Parity with elasticai_api/common/data_shard_service.py:46-212: fetch tasks
from the master, count locally-consumed records, and automatically report a
task done once its shard is fully consumed, so user training loops only call
``fetch_shard``/``report_batch_done``.
"""

import queue
import threading
import time
from collections import deque

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Sentinel: the master said "no task NOW, job not finished" (see
# fetch_task(return_wait=True)).
WAIT = object()

# Warn when this many locally-counted records sit unreported (master
# outage outlasting the RPC retry budget): the counts are safe — they
# re-flush on the next window/task boundary after reconnect — but the
# operator should know progress reporting is dark.
DEFERRED_HIGH_WATER_RECORDS = 10000


class LocalTask:
    def __init__(self, task_pb):
        self.id = task_pb.id
        self.type = task_pb.type
        self.shard = task_pb.shard
        self.size = task_pb.shard.end - task_pb.shard.start
        self.model_version = task_pb.model_version
        # Owning job under the multi-tenant scheduler (task ids are
        # only unique per job); 0 = single-job master.  Reports echo
        # it so a result lands on the dispatching job even after the
        # worker was re-assigned (docs/scheduler.md).  getattr: test
        # fakes hand in bare namespaces predating the field.
        self.job_id = getattr(task_pb, "job_id", 0)


class DataShardService:
    def __init__(self, master_client, batch_size=1, wait_poll_secs=0.5,
                 stop_check=None, telemetry_fn=None):
        """``telemetry_fn``: optional zero-arg callable returning the
        worker's live telemetry dict (Worker._telemetry_snapshot); its
        result rides every progress RPC (MasterClient.report_batch_done
        telemetry fields), so per-worker steps/s reaches the master at
        exactly the coalesced report cadence — no extra RPCs."""
        self._mc = master_client
        self._batch_size = batch_size
        self._wait_poll_secs = wait_poll_secs
        self._telemetry_fn = telemetry_fn
        self._lock = threading.Lock()
        self._pending = deque()   # tasks whose records are being consumed
        self._record_count = 0
        # Records counted locally but not yet sent to the master's
        # report_batch_done RPC — the fused driver defers per-batch
        # counts and flushes ONE RPC per window (flush is mandatory and
        # structural at task boundaries: report_task_done/failed and
        # shard auto-completion all flush first, so no progress count
        # is silently lost or double-sent).
        self._deferred_records = 0
        # Job the deferred counts belong to: the job of the most
        # recently fetched task (flushes happen at window/task
        # boundaries, before the next fetch can switch jobs).  Known
        # at-least-once edge: counts re-buffered by a failed flush and
        # re-flushed after a re-assignment land on the NEW job —
        # observability counts only, task accounting stays exact.
        # 0 = single-job master (field omitted).
        self._counts_job = 0
        self._stopped = threading.Event()
        self._stop_check = stop_check  # e.g. graceful-preemption flag
        self.exec_counters = {"batch_count": 0, "record_count": 0}

    def stop(self):
        self._stopped.set()

    def set_batch_size(self, batch_size):
        """Multi-tenant job switch: the new job may count records in a
        different default batch geometry."""
        with self._lock:
            self._batch_size = batch_size

    def _send_batch_done(self, count):
        """The progress RPC with outage protection: a failed send puts
        the counts BACK in the deferred buffer (they re-flush at the
        next window/task boundary after reconnect) instead of raising
        and stranding locally-counted records.  The buffer is one
        integer — bounded by construction — with a high-water warning
        so a long outage is visible.  Returns True when sent."""
        telemetry = None
        if self._telemetry_fn is not None:
            try:
                telemetry = self._telemetry_fn()
            except Exception as e:  # noqa: BLE001 — telemetry must
                # never fail a progress report
                logger.warning("telemetry snapshot failed: %s", e)
        kwargs = {}
        if telemetry:
            kwargs["telemetry"] = telemetry
        with self._lock:
            job = self._counts_job
        if job:
            kwargs["job_id"] = job
        try:
            # historical call shape preserved: clients (and test
            # fakes) that predate the telemetry/job piggybacks see the
            # bare positional call
            self._mc.report_batch_done(count, **kwargs)
            return True
        except Exception as e:  # noqa: BLE001 — outage outlasted retry
            with self._lock:
                self._deferred_records += count
                buffered = self._deferred_records
            logger.warning(
                "report_batch_done failed (%s); %d records re-buffered "
                "for flush after reconnect", e, buffered,
            )
            if buffered >= DEFERRED_HIGH_WATER_RECORDS:
                logger.warning(
                    "deferred progress high water: %d records counted "
                    "locally but unreported — master outage has "
                    "outlasted the RPC retry budget", buffered,
                )
            return False

    def fetch_task(self, task_type=None, wait=True, return_wait=False):
        """Fetch the next task; blocks through WAIT tasks if wait=True.

        Returns None when the master says the job is finished.  With
        ``return_wait`` a WAIT answer returns the ``WAIT`` sentinel
        instead of blocking — collective workers must not idle-spin
        inside the world (worker/worker.py leave/rejoin protocol).
        """
        while not self._stopped.is_set():
            task_pb = self._mc.get_task(task_type)
            if task_pb.id < 0:
                if task_pb.type == pb.WAIT:
                    if return_wait:
                        return WAIT
                    if wait and not (
                        self._stop_check and self._stop_check()
                    ):
                        time.sleep(self._wait_poll_secs)
                        continue
                return None
            task = LocalTask(task_pb)
            with self._lock:
                self._counts_job = task.job_id
                if task.type == pb.TRAINING:
                    # Only training tasks auto-complete via record
                    # counting; eval/predict/callback tasks are
                    # reported explicitly.
                    self._pending.append(task)
            return task

    def report_batch_done(self, batch_size=None, defer=False):
        """Count consumed records; auto-complete tasks as shards drain.

        ``defer=True`` buffers the master RPC (local accounting still
        happens immediately): the fused driver reports each batch of a
        window deferred and sends ONE coalesced ``report_batch_done``
        via ``flush_batch_done`` at the window boundary.  A shard
        draining to completion is a task boundary — it forces the flush
        regardless, so the master's progress counts are current
        whenever its task accounting changes.
        """
        done = []
        with self._lock:
            count = batch_size or self._batch_size
            self._deferred_records += count
            self._record_count += count
            self.exec_counters["batch_count"] += 1
            self.exec_counters["record_count"] += count
            while self._pending and self._record_count >= self._pending[0].size:
                task = self._pending.popleft()
                self._record_count -= task.size
                done.append((task.id, task.job_id))
            flush = self._deferred_records if (not defer or done) else 0
            if flush:
                self._deferred_records = 0
            # Snapshot inside, RPC outside: a slow/retrying master must
            # stall only this caller, not every thread entering
            # fetch_task/report_batch_done for the RPC's duration.
            counters = dict(self.exec_counters) if done else None
        if flush:
            self._send_batch_done(flush)
        for task_id, job_id in done:
            kwargs = {"job_id": job_id} if job_id else {}
            self._mc.report_task_result(task_id, exec_counters=counters,
                                        **kwargs)

    def flush_batch_done(self):
        """Send any deferred record counts in one RPC (no-op when
        nothing is buffered).  Mandatory at window boundaries, on
        preemption, and at task boundaries — report_task_done/failed
        call it structurally."""
        with self._lock:
            flush, self._deferred_records = self._deferred_records, 0
        if flush:
            self._send_batch_done(flush)

    def report_task_failed(self, task, err_message, requeue=False):
        """``requeue``: hand the task back WITHOUT consuming one of its
        retries (graceful preemption — the task isn't at fault; on a
        preemptible pool the same task could otherwise burn its whole
        retry budget on evictions and permanently fail)."""
        self.flush_batch_done()  # progress counts must precede the verdict
        with self._lock:
            try:
                was_head = self._pending and self._pending[0] is task
                self._pending.remove(task)
                # Consumption is FIFO against the head task, so only a
                # failed head can have records counted toward it — drop at
                # most its own share, never progress that belongs to other
                # pending tasks.
                if was_head:
                    self._record_count = max(
                        0, self._record_count - task.size
                    )
            except ValueError:
                pass
        kwargs = {}
        if task.job_id:
            kwargs["job_id"] = task.job_id
        self._mc.report_task_result(task.id, err_message=err_message,
                                    requeue=requeue, **kwargs)

    def report_task_done(self, task):
        self.flush_batch_done()  # progress counts must precede the verdict
        with self._lock:
            try:
                self._pending.remove(task)
            except ValueError:
                pass
            # Snapshot under the lock: the dict is mutated by
            # report_batch_done from other threads, and the gRPC client
            # iterates it during serialization.
            counters = dict(self.exec_counters)
        kwargs = {}
        if task.job_id:
            kwargs["job_id"] = task.job_id
        self._mc.report_task_result(task.id, exec_counters=counters,
                                    **kwargs)


class RecordIndexService(DataShardService):
    """Index-level sharding for map-style datasets
    (reference elasticai_api data_shard_service.py:161-212): a background
    thread drains tasks from the master into a queue of record indices, so
    a torch-style ``Dataset.__getitem__`` can consume dynamic shards
    without knowing about tasks.
    """

    def __init__(self, master_client, batch_size=1, queue_size=4096):
        super().__init__(master_client, batch_size=batch_size)
        self._index_queue = queue.Queue(maxsize=queue_size)
        self._fetcher = threading.Thread(
            target=self._fill_indices, name="record-index-fetcher",
            daemon=True,
        )
        self._exhausted = threading.Event()
        self._fetcher.start()

    def _fill_indices(self):
        try:
            while not self._stopped.is_set():
                task = self.fetch_task(task_type=pb.TRAINING, wait=True)
                if task is None:
                    break
                if task.shard.record_indices:
                    indices = list(task.shard.record_indices)
                else:
                    indices = list(
                        range(task.shard.start, task.shard.end)
                    )
                for index in indices:
                    while not self._stopped.is_set():
                        try:
                            self._index_queue.put(index, timeout=1.0)
                            break
                        except queue.Full:
                            continue
        except Exception:  # noqa: BLE001 — master gone: end of stream
            pass
        finally:
            self._exhausted.set()
            try:
                self._index_queue.put_nowait(None)
            except queue.Full:
                pass

    def fetch_record_index(self, timeout=60.0):
        """Next record index, or None when the job has no more data."""
        if self._exhausted.is_set() and self._index_queue.empty():
            return None
        index = self._index_queue.get(timeout=timeout)
        if index is None:
            self._index_queue.put(None)  # keep signaling other consumers
            return None
        return index
