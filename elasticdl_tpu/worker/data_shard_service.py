"""Client-side dynamic sharding protocol.

Parity with elasticai_api/common/data_shard_service.py:46-212: fetch tasks
from the master, count locally-consumed records, and automatically report a
task done once its shard is fully consumed, so user training loops only call
``fetch_shard``/``report_batch_done``.
"""

import threading
import time
from collections import deque

from elasticdl_tpu.proto import elastic_pb2 as pb


class LocalTask:
    def __init__(self, task_pb):
        self.id = task_pb.id
        self.type = task_pb.type
        self.shard = task_pb.shard
        self.size = task_pb.shard.end - task_pb.shard.start
        self.model_version = task_pb.model_version


class DataShardService:
    def __init__(self, master_client, batch_size=1, wait_poll_secs=0.5):
        self._mc = master_client
        self._batch_size = batch_size
        self._wait_poll_secs = wait_poll_secs
        self._lock = threading.Lock()
        self._pending = deque()   # tasks whose records are being consumed
        self._record_count = 0
        self.exec_counters = {"batch_count": 0, "record_count": 0}

    def fetch_task(self, task_type=None, wait=True):
        """Fetch the next task; blocks through WAIT tasks if wait=True.

        Returns None when the master says the job is finished.
        """
        while True:
            task_pb = self._mc.get_task(task_type)
            if task_pb.id < 0:
                if task_pb.type == pb.WAIT and wait:
                    time.sleep(self._wait_poll_secs)
                    continue
                return None
            task = LocalTask(task_pb)
            if task.type == pb.TRAINING:
                # Only training tasks auto-complete via record counting;
                # eval/predict/callback tasks are reported explicitly.
                with self._lock:
                    self._pending.append(task)
            return task

    def report_batch_done(self, batch_size=None):
        """Count consumed records; auto-complete tasks as shards drain."""
        count = batch_size or self._batch_size
        self._mc.report_batch_done(count)
        with self._lock:
            self._record_count += count
            self.exec_counters["batch_count"] += 1
            self.exec_counters["record_count"] += count
            while self._pending and self._record_count >= self._pending[0].size:
                task = self._pending.popleft()
                self._record_count -= task.size
                self._mc.report_task_result(
                    task.id, exec_counters=self.exec_counters
                )

    def report_task_failed(self, task, err_message):
        with self._lock:
            try:
                self._pending.remove(task)
                # Drop records consumed from the abandoned task so they
                # don't count toward the next task's completion.
                self._record_count = 0
            except ValueError:
                pass
        self._mc.report_task_result(task.id, err_message=err_message)

    def report_task_done(self, task):
        with self._lock:
            try:
                self._pending.remove(task)
            except ValueError:
                pass
        self._mc.report_task_result(task.id, exec_counters=self.exec_counters)
