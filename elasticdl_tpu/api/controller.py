"""Wrap-your-own-loop elasticity — the ``elasticai_api`` analog.

Parity with elasticai_api/common/base_controller.py:48-186 and
elasticai_api/pytorch/controller.py:97-203, redesigned for JAX: instead of
re-initializing a Horovod ring, a rendezvous-epoch change triggers
``jax.distributed`` re-initialization (multi-host) and/or a trainer
``rebuild`` over the new mesh, which re-shards state and re-compiles the
step.  The fixed-global-batch rule is the reference's
``backward_passes_per_step`` math: per-worker accumulation count =
global_batch_num // world_size, +1 for ranks < remainder
(pytorch/controller.py:186-198).
"""

import functools
import time

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_SECS_TO_CHECK_RENDEZVOUS = 20.0


def compute_accum_steps(global_batch_num, rank, world_size):
    """Microbatch count for one worker under a fixed global batch."""
    if world_size <= 0:
        return global_batch_num
    base = global_batch_num // world_size
    remainder = global_batch_num % world_size
    return max(1, base + (1 if rank < remainder else 0))


class RendezvousManager:
    """Tracks the master's membership epoch for this worker."""

    def __init__(self, master_client):
        self._mc = master_client
        self.rendezvous_id = -1
        self.rank = -1
        self.world_size = 0
        self.coordinator_addr = ""

    def poll(self, wait=True, poll_secs=0.5, timeout=120.0):
        """Refresh (rank, world). Returns True if the epoch changed."""
        deadline = time.time() + timeout
        while True:
            res = self._mc.get_comm_rank()
            if res.rank_id >= 0 or not wait:
                break
            if time.time() > deadline:
                raise TimeoutError(
                    "worker never entered the rendezvous world"
                )
            time.sleep(poll_secs)
        changed = res.rendezvous_id != self.rendezvous_id
        self.rendezvous_id = res.rendezvous_id
        self.rank = res.rank_id
        self.world_size = res.world_size
        self.coordinator_addr = res.coordinator_addr
        return changed


class ElasticCollectiveController:
    """Init-once, re-rendezvous-periodically, retry-on-failure loop driver.

    Usage (mirrors the reference's ``elastic_run`` pattern):

        controller = ElasticCollectiveController(mc, trainer, shard_service,
                                                 global_batch_num=8)
        elastic_train = controller.elastic_run(train_one_batch)
        with controller.scope():
            for batch in batches:
                elastic_train(batch)
    """

    def __init__(
        self,
        master_client,
        trainer,
        data_shard_service=None,
        global_batch_num=1,
        check_secs=DEFAULT_SECS_TO_CHECK_RENDEZVOUS,
        mesh_builder=None,
        max_retries=3,
        epoch_wait_secs=60.0,
        check_steps=None,
    ):
        """``check_steps``: re-check the rendezvous every N wrapped
        calls instead of every ``check_secs`` seconds.  Step-count
        cadence is the SPMD-safe choice for multi-process collectives:
        every member of an epoch enters it at the same logical point
        and runs the same step sequence, so all members observe a new
        epoch at the SAME collective index and leave the old world
        together — a wall-clock cadence lets one rank leave while a
        peer is already blocked inside a collective the leaver will
        never join."""
        self._mc = master_client
        self._trainer = trainer
        self._shard_service = data_shard_service
        self._global_batch_num = global_batch_num
        self._check_secs = check_secs
        self._mesh_builder = mesh_builder
        self._max_retries = max_retries
        self._epoch_wait_secs = epoch_wait_secs
        self._check_steps = check_steps
        self._steps_since_check = 0
        self._rendezvous = RendezvousManager(master_client)
        self._last_check = 0.0
        self._first_init_done = False

    # -- world management ---------------------------------------------------

    def _reinit_world(self):
        rdzv = self._rendezvous
        logger.info(
            "world epoch %d: rank=%d world=%d",
            rdzv.rendezvous_id, rdzv.rank, rdzv.world_size,
        )
        if hasattr(self._trainer, "snapshot_to_host"):
            # (Re-)forming a master-coordinated world clears XLA
            # backends (parallel/distributed.py), which invalidates
            # every device array of the old epoch — including the
            # trainer's FIRST-init local-mesh state — so pull state to
            # host while the local backend is still alive.
            self._trainer.snapshot_to_host()
        if self._mesh_builder is not None:
            # Multi-host path: the builder may call
            # jax.distributed.initialize(coordinator, world, rank) and
            # construct the new global mesh.
            mesh = self._mesh_builder(
                rdzv.rank, rdzv.world_size, rdzv.coordinator_addr
            )
            self._trainer.rebuild(mesh)
        accum = compute_accum_steps(
            self._global_batch_num, rdzv.rank, rdzv.world_size
        )
        if hasattr(self._trainer, "set_accum_steps"):
            self._trainer.set_accum_steps(accum)

    def init_world_if_needed(self, force=False):
        now = time.time()
        if not force:
            if self._check_steps is not None:
                if (self._first_init_done
                        and self._steps_since_check < self._check_steps):
                    return False
            elif now - self._last_check < self._check_secs:
                return False
        self._steps_since_check = 0
        self._last_check = now
        changed = self._rendezvous.poll(wait=not self._first_init_done)
        if self._rendezvous.rank < 0:
            # Mid-churn the committed world can exclude this host
            # (poll(wait=False) still reports the new epoch).  Never
            # build a coordination client with process_id=-1 — and
            # never stay attached to the PREVIOUS epoch either: the
            # master reaps its service after reap_secs, which kills an
            # attached client from C++.  Detach to single-process mode
            # and re-announce LOOP_START so the next commit re-admits
            # us (epoch bumps again -> rank >= 0 -> rebuild).
            if changed:
                self.leave_world()
            # Announce even when the id did NOT change: a master
            # restarted from its journal re-arms at journaled+1, which
            # can EQUAL the un-journaled id this worker glimpsed just
            # before the crash — same id, empty committed world,
            # rank=-1 — and with no pending member the restarted
            # master would never commit again.  LOOP_START is
            # idempotent on the master (add_worker no-ops while the
            # host is already pending), so repeating it at the check
            # cadence is safe.
            self._mc.report_train_loop_status(pb.LOOP_START)
            return False
        if changed or not self._first_init_done:
            self._reinit_world()
            self._first_init_done = True
            return True
        return False

    @property
    def world_size(self):
        return self._rendezvous.world_size

    def step_check(self, steps=1):
        """One training step's epoch check (driven mode — a managed
        Worker calls this instead of wrapping its loop in
        elastic_run): counts the step for the check_steps cadence and
        re-forms the world when the cadence says to look.  The fused
        driver passes its window length as ``steps`` (one check per
        window, counted as the window's steps BEFORE they run; with
        windows clamped to ``steps_to_check`` a check fires at most
        window-1 steps earlier than the per-step loop's — a safe bias
        for a poll that only re-forms on a real epoch change)."""
        self._steps_since_check += steps
        return self.init_world_if_needed()

    def steps_to_check(self):
        """Steps until the next check_steps epoch-check boundary (None
        when the cadence is time-based) — the fused driver's window
        clamp."""
        if self._check_steps is None:
            return None
        return max(1, self._check_steps - self._steps_since_check)

    def leave_world(self):
        """Temporarily exit the collective world (idle worker, no task
        in hand): snapshot state, drop the coordination client, restore
        single-process mode.  Peers re-form without us; rejoin_world
        re-enters.  Staying attached while idle would both stall every
        peer's collectives AND get this process terminated when the
        master reaps the epoch's service out from under its heartbeat
        thread."""
        from elasticdl_tpu.parallel.distributed import (
            reset_single_process,
        )

        if hasattr(self._trainer, "snapshot_to_host"):
            self._trainer.snapshot_to_host()
        reset_single_process()

    def rejoin_world(self, timeout=120.0):
        """Re-enter the committed world after leave_world (the caller
        re-announced itself via LOOP_START) and rebuild for it."""
        self._rendezvous.poll(wait=True, timeout=timeout)
        self._reinit_world()
        # This WAS the world init: without this, the next step_check
        # would re-run _reinit_world and spuriously disconnect from the
        # live epoch service mid-epoch.
        self._first_init_done = True
        self._last_check = time.time()
        self._steps_since_check = 0

    def await_new_epoch(self, timeout=60.0, poll_secs=0.5):
        """Block until the master commits a DIFFERENT epoch, then
        rebuild for it.  The recovery path after an in-band collective
        failure: the failed world is dead, so retrying before the
        master removes the lost peer and re-forms membership would
        just fail again (reference allreduce_trainer.py:77-91 —
        Horovod survivors wait on a new rendezvous).  Returns True if
        a new epoch arrived."""
        deadline = time.time() + timeout
        epoch_seen = False
        announced = False
        while time.time() < deadline:
            if self._rendezvous.poll(wait=False):
                epoch_seen = True
            # Guard on rank >= 0 (ADVICE r5 low): a new epoch can
            # commit WITHOUT this host (the master batches joins behind
            # a grace window), and _reinit_world with rank=-1 would
            # build a coordination client with process_id=-1 —
            # undefined/fatal.  Keep polling until we are a member of
            # some committed epoch.
            if epoch_seen and self._rendezvous.rank >= 0:
                self._reinit_world()
                self._last_check = time.time()
                self._steps_since_check = 0
                return True
            if not announced and (
                epoch_seen or self._rendezvous.rank < 0
            ):
                # Excluded from the new world — or orphaned at an
                # UNCHANGED id by a master that restarted from its
                # journal at exactly the id we glimpsed before the
                # crash (rank=-1 against its empty committed world, so
                # no new epoch will ever commit unless we announce):
                # detach from the doomed old epoch (its service gets
                # reaped) and re-announce so the master's next commit
                # re-admits us.
                self.leave_world()
                self._mc.report_train_loop_status(pb.LOOP_START)
                announced = True
            time.sleep(poll_secs)
        return False

    # -- loop driver ----------------------------------------------------------

    def elastic_run(self, func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            self._steps_since_check += 1
            self.init_world_if_needed()
            err = None
            for _ in range(self._max_retries):
                try:
                    result = func(*args, **kwargs)
                    if self._shard_service is not None:
                        self._shard_service.report_batch_done()
                    return result
                except Exception as e:  # noqa: BLE001 — comm failures
                    err = e
                    logger.warning(
                        "step failed (%s); re-rendezvousing and retrying", e
                    )
                    time.sleep(1.0)
                    # In a multi-process world, prefer waiting for a
                    # NEW epoch: the failed world cannot succeed until
                    # the master removes the lost peer.  Fall back to a
                    # forced re-init if none arrives (transient error,
                    # membership unchanged) — also the whole story for
                    # single-process worlds.
                    recovered = (
                        self._rendezvous.world_size > 1
                        and self.await_new_epoch(
                            timeout=self._epoch_wait_secs)
                    )
                    if not recovered:
                        self.init_world_if_needed(force=True)
            raise RuntimeError(
                "step failed after %d re-rendezvous retries"
                % self._max_retries
            ) from err

        return wrapper

    class _Scope:
        def __init__(self, mc):
            self._mc = mc

        def __enter__(self):
            self._mc.report_train_loop_status(pb.LOOP_START)
            return self

        def __exit__(self, *exc):
            self._mc.report_train_loop_status(pb.LOOP_END)
            return False

    def scope(self):
        """Joins/leaves the rendezvous world around the training loop."""
        return self._Scope(self._mc)
