"""Elastic map-style datasets (reference: elasticai_api/pytorch/dataset.py).

``ElasticDataset`` wraps any indexable source so that ``__getitem__``
consumes master-assigned record indices instead of the loader's own
sampler — the trick that makes a stock PyTorch/NumPy training loop
elastic: whatever records a dead worker was holding are re-queued by the
master and handed to the surviving workers.  ``__len__`` is reported as a
very large number (the reference uses sys.maxsize) because the true
amount of data a given worker will see is decided dynamically.
"""

import sys

from elasticdl_tpu.worker.data_shard_service import RecordIndexService


class ElasticDataset:
    def __init__(self, source, master_client, batch_size=1):
        """source: anything supporting source[i] for global record i."""
        self._source = source
        self.shard_service = RecordIndexService(
            master_client, batch_size=batch_size
        )

    def __len__(self):
        return sys.maxsize

    def __getitem__(self, _index):
        """Ignores the sampler's index; pulls the next dynamic index."""
        index = self.shard_service.fetch_record_index()
        if index is None:
            raise IndexError("no more records (job finished)")
        return self._source[index]

    def report_batch_done(self, batch_size=None):
        self.shard_service.report_batch_done(batch_size)

    def stop(self):
        self.shard_service.stop()
