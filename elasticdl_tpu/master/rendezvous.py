"""Elastic collective membership — rendezvous epochs.

TPU-native replacement for the master-hosted Horovod rendezvous
(elasticdl/python/master/rendezvous_server.py:34-167).  Where Horovod
rebuilds a Gloo ring, JAX bakes the device mesh into the compiled step; so
membership changes are modeled as *epochs*: any join/leave bumps
``rendezvous_id``, and workers observing a new id tear down their collective
context (jax.distributed / compiled-step cache) and rebuild it for the new
world.  Joins are batched behind a short grace window so a burst of
relaunched workers triggers one re-compile, not many.
"""

import threading
import time

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class RendezvousServer:
    def __init__(self, grace_secs=2.0):
        self._lock = threading.Lock()
        self._grace_secs = grace_secs
        self._cur_hosts = []     # committed world, sorted by join order
        self._next_hosts = []    # pending world
        self._rendezvous_id = 0
        self._last_change = None
        self._coordinator_addr = ""

    def set_coordinator_addr(self, addr):
        self._coordinator_addr = addr

    @property
    def rendezvous_id(self):
        with self._lock:
            return self._rendezvous_id

    @property
    def world(self):
        with self._lock:
            return list(self._cur_hosts)

    def add_worker(self, host):
        with self._lock:
            if host not in self._next_hosts:
                self._next_hosts.append(host)
                self._last_change = time.time()
                logger.info("rendezvous: worker %s joining", host)

    def remove_worker(self, host):
        with self._lock:
            if host in self._next_hosts:
                self._next_hosts.remove(host)
                self._last_change = time.time()
                logger.info("rendezvous: worker %s leaving", host)

    def _maybe_commit(self):
        # caller holds the lock
        if (
            self._next_hosts != self._cur_hosts
            and self._last_change is not None
            and time.time() - self._last_change >= self._grace_secs
        ):
            self._cur_hosts = list(self._next_hosts)
            self._rendezvous_id += 1
            logger.info(
                "rendezvous epoch %d: world=%s",
                self._rendezvous_id, self._cur_hosts,
            )

    def get_comm_rank(self, host):
        """Return (rank, world_size, rendezvous_id, coordinator_addr).

        rank == -1 means the host is not (yet) in the committed world and
        should keep polling.
        """
        with self._lock:
            self._maybe_commit()
            if host in self._cur_hosts:
                rank = self._cur_hosts.index(host)
            else:
                rank = -1
            return (
                rank,
                len(self._cur_hosts),
                self._rendezvous_id,
                self._coordinator_addr,
            )
