"""Elastic collective membership — rendezvous epochs.

TPU-native replacement for the master-hosted Horovod rendezvous
(elasticdl/python/master/rendezvous_server.py:34-167).  Where Horovod
rebuilds a Gloo ring, JAX bakes the device mesh into the compiled step; so
membership changes are modeled as *epochs*: any join/leave bumps
``rendezvous_id``, and workers observing a new id tear down their collective
context (jax.distributed / compiled-step cache) and rebuild it for the new
world.  Joins are batched behind a short grace window so a burst of
relaunched workers triggers one re-compile, not many.
"""

import threading
import time

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class RendezvousServer:
    def __init__(self, grace_secs=2.0, coordinator_factory=None):
        """``coordinator_factory(world_size) -> addr`` (optional): run
        at every epoch commit to stand up that epoch's coordination
        plane — in production ``MasterCoordinationService.start_epoch``
        (parallel/distributed.py), which keeps the JAX coordination
        service on the MASTER so worker churn can never strand the
        survivors.  Without a factory the address set via
        ``set_coordinator_addr`` is advertised unchanged (legacy:
        worker 0 hosts the service)."""
        self._lock = threading.Lock()
        self._grace_secs = grace_secs
        self._coordinator_factory = coordinator_factory
        self._cur_hosts = []     # committed world, sorted by join order
        self._next_hosts = []    # pending world
        self._rendezvous_id = 0
        self._last_change = None
        self._coordinator_addr = ""

    def set_coordinator_addr(self, addr):
        with self._lock:
            self._coordinator_addr = addr

    @property
    def rendezvous_id(self):
        with self._lock:
            return self._rendezvous_id

    @property
    def world(self):
        with self._lock:
            return list(self._cur_hosts)

    def add_worker(self, host):
        with self._lock:
            if host not in self._next_hosts:
                self._next_hosts.append(host)
                self._last_change = time.time()
                logger.info("rendezvous: worker %s joining", host)

    def remove_worker(self, host):
        with self._lock:
            if host in self._next_hosts:
                self._next_hosts.remove(host)
                self._last_change = time.time()
                logger.info("rendezvous: worker %s leaving", host)

    def _maybe_commit_locked(self):
        if (
            self._next_hosts != self._cur_hosts
            and self._last_change is not None
            and time.time() - self._last_change >= self._grace_secs
        ):
            new_hosts = list(self._next_hosts)
            addr = self._coordinator_addr
            if self._coordinator_factory is not None:
                # Stand the epoch's coordination plane up BEFORE
                # publishing the epoch: a factory failure (port grabbed
                # between probe and bind, resource exhaustion) must not
                # commit a new rendezvous_id pointing at the previous
                # epoch's address.  Deferring re-arms the grace window,
                # so the commit retries.
                try:
                    addr = self._coordinator_factory(len(new_hosts))
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "coordinator factory failed (%s); deferring "
                        "epoch commit", e,
                    )
                    self._last_change = time.time()
                    return
            self._cur_hosts = new_hosts
            self._rendezvous_id += 1
            self._coordinator_addr = addr
            logger.info(
                "rendezvous epoch %d: world=%s coordinator=%s",
                self._rendezvous_id, self._cur_hosts,
                self._coordinator_addr,
            )

    def get_comm_rank(self, host):
        """Return (rank, world_size, rendezvous_id, coordinator_addr).

        rank == -1 means the host is not (yet) in the committed world and
        should keep polling.
        """
        with self._lock:
            self._maybe_commit_locked()
            if host in self._cur_hosts:
                rank = self._cur_hosts.index(host)
            else:
                rank = -1
            return (
                rank,
                len(self._cur_hosts),
                self._rendezvous_id,
                self._coordinator_addr,
            )
