"""Elastic collective membership — rendezvous epochs.

TPU-native replacement for the master-hosted Horovod rendezvous
(elasticdl/python/master/rendezvous_server.py:34-167).  Where Horovod
rebuilds a Gloo ring, JAX bakes the device mesh into the compiled step; so
membership changes are modeled as *epochs*: any join/leave bumps
``rendezvous_id``, and workers observing a new id tear down their collective
context (jax.distributed / compiled-step cache) and rebuild it for the new
world.  Joins are batched behind a short grace window so a burst of
relaunched workers triggers one re-compile, not many.
"""

import threading
import time

from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class RendezvousServer:
    def __init__(self, grace_secs=2.0, coordinator_factory=None,
                 journal=None, initial_epoch=0, name=""):
        """``coordinator_factory(world_size) -> addr`` (optional): run
        at every epoch commit to stand up that epoch's coordination
        plane — in production ``MasterCoordinationService.start_epoch``
        (parallel/distributed.py), which keeps the JAX coordination
        service on the MASTER so worker churn can never strand the
        survivors.  Without a factory the address set via
        ``set_coordinator_addr`` is advertised unchanged (legacy:
        worker 0 hosts the service).

        ``journal``/``initial_epoch`` (master/journal.py): every epoch
        commit is made durable BEFORE it is published (staged under
        the lock, journaled outside it, only then visible to
        ``get_comm_rank``), so a restarted master's ``initial_epoch =
        journaled_id + 1`` is strictly above any id a surviving
        worker can hold.  Reconnecting workers see rank=-1 against
        the empty committed world, re-announce LOOP_START (the
        controller announces on rank=-1 even when the id looks
        unchanged — defense in depth should the journal tail ever be
        lost to more than a crash), and re-form at the first
        post-restart commit."""
        # ``name``: log/trace label — under the multi-tenant scheduler
        # every job owns its own rendezvous epoch space, and interleaved
        # multi-job logs must name whose epoch committed.
        self._name = name
        self._lock = threading.Lock()
        self._grace_secs = grace_secs
        self._coordinator_factory = coordinator_factory
        self._journal = journal
        self._cur_hosts = []     # committed world, sorted by join order
        self._next_hosts = []    # pending world
        self._rendezvous_id = int(initial_epoch)
        self._last_change = None
        self._coordinator_addr = ""
        # True while a staged commit is being made durable (journal
        # write outside the lock); blocks a second concurrent stage
        # from minting a colliding id.
        self._commit_inflight = False

    def set_coordinator_addr(self, addr):
        with self._lock:
            self._coordinator_addr = addr

    @property
    def rendezvous_id(self):
        with self._lock:
            return self._rendezvous_id

    @property
    def world(self):
        with self._lock:
            return list(self._cur_hosts)

    def add_worker(self, host):
        with self._lock:
            if host not in self._next_hosts:
                self._next_hosts.append(host)
                self._last_change = time.time()
                logger.info("rendezvous: worker %s joining", host)

    def remove_worker(self, host):
        with self._lock:
            if host in self._next_hosts:
                self._next_hosts.remove(host)
                self._last_change = time.time()
                logger.info("rendezvous: worker %s leaving", host)

    def _maybe_stage_commit_locked(self):
        """Stage a pending membership change WITHOUT publishing it:
        returns ``{"hosts", "n", "addr"}`` for the caller to journal
        (file I/O, outside the lock — EL006) and then publish, or
        None.  While one stage is in flight no second commit can be
        minted, so ids never collide."""
        if self._commit_inflight:
            return None
        if (
            self._next_hosts != self._cur_hosts
            and self._last_change is not None
            and time.time() - self._last_change >= self._grace_secs
        ):
            new_hosts = list(self._next_hosts)
            addr = self._coordinator_addr
            if self._coordinator_factory is not None:
                # Stand the epoch's coordination plane up BEFORE
                # publishing the epoch: a factory failure (port grabbed
                # between probe and bind, resource exhaustion) must not
                # commit a new rendezvous_id pointing at the previous
                # epoch's address.  Deferring re-arms the grace window,
                # so the commit retries.
                try:
                    addr = self._coordinator_factory(len(new_hosts))
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "coordinator factory failed (%s); deferring "
                        "epoch commit", e,
                    )
                    self._last_change = time.time()
                    return None
            self._commit_inflight = True
            return {
                "hosts": new_hosts, "n": self._rendezvous_id + 1,
                "addr": addr,
            }
        return None

    def get_comm_rank(self, host):
        """Return (rank, world_size, rendezvous_id, coordinator_addr).

        rank == -1 means the host is not (yet) in the committed world and
        should keep polling.
        """
        with self._lock:
            staged = self._maybe_stage_commit_locked()
        if staged is not None:
            # Durable BEFORE visible: no worker may observe an epoch
            # id the journal could lose.  The flush is synchronous and
            # deliberate — commits are rare (one per membership
            # change, behind a grace window) — and because nothing is
            # published until the record is on disk, a restarted
            # master's ``initial_epoch = journaled + 1`` is strictly
            # above every id any worker can hold, however many
            # commits were in flight at the crash.  Concurrent pollers
            # meanwhile see the previous epoch and simply poll again.
            if self._journal is not None:
                try:
                    self._journal.append(
                        {"ev": "rdzv", "n": staged["n"],
                         "hosts": list(staged["hosts"])}
                    )
                    self._journal.flush()
                except Exception:
                    # Un-stage so a later poll can retry the commit;
                    # nothing was published, so no worker saw the id.
                    with self._lock:
                        self._commit_inflight = False
                    raise
            with self._lock:
                self._cur_hosts = staged["hosts"]
                self._rendezvous_id = staged["n"]
                self._coordinator_addr = staged["addr"]
                self._commit_inflight = False
                logger.info(
                    "rendezvous%s epoch %d: world=%s coordinator=%s",
                    " [%s]" % self._name if self._name else "",
                    self._rendezvous_id, self._cur_hosts,
                    self._coordinator_addr,
                )
            # Epoch commits run inside a worker's get_comm_rank server
            # span, so the re-form lands in the polling worker's trace.
            attrs = {"epoch": staged["n"],
                     "world_size": len(staged["hosts"])}
            if self._name:
                attrs["job"] = self._name
            tracing.event("rendezvous.epoch", **attrs)
        with self._lock:
            if host in self._cur_hosts:
                rank = self._cur_hosts.index(host)
            else:
                rank = -1
            return (
                rank,
                len(self._cur_hosts),
                self._rendezvous_id,
                self._coordinator_addr,
            )
