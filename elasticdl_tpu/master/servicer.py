"""Master gRPC servicer.

Implements the control-plane RPCs (parity with
elasticdl/python/master/servicer.py:61-198): task dispatch with WAIT-task
logic for idle workers, task result accounting, rendezvous rank queries,
train-loop membership, evaluation metric ingestion and version reports.
"""

import functools
import threading
import time

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.proto import rpc
from elasticdl_tpu.utils import grpc_utils, tensor_codec, tracing
from elasticdl_tpu.utils import hist as hist_mod
from elasticdl_tpu.utils.grpc_utils import rpc_error_guard
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.master.task_manager import wait_task_pb

logger = get_logger(__name__)


def _timed_rpc(method):
    """Feed each handled RPC's wall time into the servicer's Timing —
    behind the mean sits a histogram (utils/hist.py), so the master's
    RPC handle time is a real p99 on /metrics
    (elasticdl_master_rpc_handle_seconds{method=}).  Durations are
    measured with local starts (concurrent handler threads — the
    shared timeit starts dict would corrupt)."""
    name = "rpc." + method.__name__

    @functools.wraps(method)
    def wrapper(self, request, _context=None):
        t0 = time.perf_counter()
        try:
            return method(self, request, _context)
        finally:
            self.timing.observe(name, time.perf_counter() - t0)

    return wrapper


class MasterServicer:
    def __init__(
        self,
        task_manager,
        rendezvous_server=None,
        evaluation_service=None,
        worker_manager=None,
        journal=None,
        job_id=0,
    ):
        # Multi-tenant scheduler (master/scheduler.py): each admitted
        # job gets its OWN MasterServicer, so the per-worker telemetry
        # aggregation below is keyed per job by construction — two
        # jobs' workers can never collide in one aggregate.  ``job_id``
        # makes a misroute loud instead of silent: a progress report
        # stamped for a different job is dropped, never folded in.
        # 0 = the single-job master (job scoping off).
        self._job_id = job_id
        self._task_manager = task_manager
        self._rendezvous = rendezvous_server
        self._evaluation_service = evaluation_service
        self._worker_manager = worker_manager
        self._lock = threading.Lock()
        # Progress events stream to the job journal BUFFERED (they are
        # the hot path; a crash loses at most one flush window of
        # observability counts — task accounting is exact).  Appends
        # run outside self._lock (EL006).
        self._journal = journal
        self._version = 0
        self.training_params = None
        self.worker_record_counts = {}  # worker_id -> records processed
        self.worker_exec_counters = {}  # counter name -> total
        # Per-worker live training telemetry piggybacked on the
        # coalesced progress RPCs (docs/observability.md): worker_id ->
        # {steps_per_sec, sync_fraction, push_staleness, window_size,
        # steps_done, age}.  The per-job aggregate over these series is
        # the sensor input the multi-tenant resize controller (ROADMAP
        # item 5) reads from /status and /metrics.
        self.worker_telemetry = {}
        # Handle-time phases for the hot control-plane RPCs
        # (_timed_rpc); .histograms() renders on /metrics.
        self.timing = Timing()
        # Per-worker / per-job step-time distributions: EXACT merges
        # of the sparse histogram deltas workers piggyback on progress
        # RPCs (utils/hist.py fixed bounds — true p50/p99, not means
        # of means), plus the straggler detector's sweep state
        # (docs/observability.md).  All under self._lock.
        self.worker_step_hist = {}     # worker_id -> snapshot dict
        self.job_step_hist = hist_mod.empty_snapshot()
        self._straggler_prev = {}      # worker_id -> snapshot at sweep
        self._straggler_state = {}     # worker_id -> {"flagged": n,
        #                                "p50_ms": x, "ratio": r}
        # PS recovery state from generation-tagged version reports
        # (docs/ps_recovery.md): ps_id -> {generation, version,
        # durable_version}.  Observability only (status page, drills);
        # not journaled — a restarted master re-learns it from the next
        # cadence of reports.
        self.ps_shard_state = {}

    def restore_from_journal(self, state):
        """Master restart: resume the version high-water mark and the
        per-worker progress counts from the replayed journal."""
        with self._lock:
            self._version = max(self._version, state.model_version)
            for worker_id, n in state.worker_records.items():
                self.worker_record_counts[worker_id] = n

    @property
    def model_version(self):
        with self._lock:
            return self._version

    # -- task dispatch ------------------------------------------------------

    @rpc_error_guard
    @_timed_rpc
    def get_task(self, request, _context=None):
        res = pb.GetTaskResponse()
        task = self._task_manager.get(request.worker_id)
        if task is not None:
            task.to_pb(out=res.task)
            return res
        if not self._task_manager.finished():
            # Work may reappear (retries, new epochs, eval jobs): park the
            # worker instead of letting it exit.
            res.task.CopyFrom(wait_task_pb())
        else:
            res.task.id = -1
            res.task.type = pb.TRAINING  # no more work: worker exits
        return res

    @rpc_error_guard
    @_timed_rpc
    def report_task_result(self, request, _context=None):
        success = not request.err_message
        if request.exec_counters:
            # job-level execution counters piggybacked on task reports
            # (reference data_shard_service.py:100-109)
            with self._lock:
                for name, value in request.exec_counters.items():
                    self.worker_exec_counters[name] = max(
                        self.worker_exec_counters.get(name, 0), value
                    )
        result = self._task_manager.report(
            request.task_id, success, request.err_message,
            requeue=request.requeue,
        )
        # Flight-recorder breadcrumbs in the CALLER's trace (the server
        # span set by TraceServerInterceptor): a drill can follow one
        # task from dispatch through its completion/re-queue across the
        # worker and master rings.
        if success:
            tracing.event("task.completed", task=request.task_id)
        elif request.requeue:
            tracing.event("task.requeued", task=request.task_id)
        else:
            tracing.event("task.fail_reported", task=request.task_id,
                          error=request.err_message[:200])
        if (
            self._evaluation_service is not None
            and result.task is not None
            and result.task.type == pb.EVALUATION
            # A permanently-failed eval task must still count toward job
            # completion, or one bad shard wedges evaluation forever.
            and (result.ok or result.permanent_failure)
        ):
            self._evaluation_service.complete_task(
                model_version=result.task.model_version
            )
        return pb.Empty()

    @rpc_error_guard
    @_timed_rpc
    def report_batch_done(self, request, _context=None):
        if self._job_id and request.job_id and (
            request.job_id != self._job_id
        ):
            # A shared-pool worker's progress report for a DIFFERENT
            # job: counting its records (or its steps/s telemetry)
            # here would corrupt this job's aggregate — the exact
            # collision the job-scoped proto fields exist to prevent.
            logger.warning(
                "progress report for job %d dropped by job %d's "
                "servicer (routing bug upstream?)",
                request.job_id, self._job_id,
            )
            return pb.Empty()
        with self._lock:
            prev = self.worker_record_counts.get(request.worker_id, 0)
            self.worker_record_counts[request.worker_id] = (
                prev + request.record_count
            )
            if request.steps_done > 0:
                # Telemetry rides the progress report (proto fields
                # 3-7); absent fields decode as 0 — a worker predating
                # the telemetry piggyback just never lands here.
                now = time.time()
                self.worker_telemetry[request.worker_id] = {
                    "steps_per_sec": request.steps_per_sec,
                    "sync_fraction": request.sync_fraction,
                    "push_staleness": request.push_staleness,
                    "window_size": request.window_size,
                    "steps_done": request.steps_done,
                    "ts": now,
                }
                # Bound the dict even when nothing polls telemetry()
                # (--status_port off is the default): past a generous
                # live-worker count, drop long-dead entries here too.
                if len(self.worker_telemetry) > 64:
                    cutoff = now - self.TELEMETRY_EVICT_SECS
                    for worker_id in [
                        w for w, t in self.worker_telemetry.items()
                        if t["ts"] < cutoff
                    ]:
                        del self.worker_telemetry[worker_id]
                        # Step-hist state rides the same eviction: a
                        # long-dead worker's distribution stays summed
                        # into the JOB histogram (history is history)
                        # but leaves the per-worker views.
                        self.worker_step_hist.pop(worker_id, None)
                        self._straggler_prev.pop(worker_id, None)
                        self._straggler_state.pop(worker_id, None)
            if request.hist_delta:
                # Compact per-worker histogram deltas piggybacked on
                # the progress report (utils/hist.py sparse encoding;
                # fixed shared bucket bounds make the merge EXACT):
                # per-worker accumulators feed the straggler sweep,
                # the per-job accumulator feeds the true p50/p99 step
                # time on /status and /metrics.
                deltas = hist_mod.decode_deltas(request.hist_delta)
                step = deltas.get("step_time")
                if step is not None:
                    acc = self.worker_step_hist.setdefault(
                        request.worker_id, hist_mod.empty_snapshot())
                    hist_mod.merge_delta(acc, step)
                    hist_mod.merge_delta(self.job_step_hist, step)
        if self._journal is not None:
            self._journal.append(
                {"ev": "batch", "w": request.worker_id,
                 "n": request.record_count}
            )
        return pb.Empty()

    # -- rendezvous ---------------------------------------------------------

    @rpc_error_guard
    def get_comm_rank(self, request, _context=None):
        res = pb.GetCommRankResponse()
        if self._rendezvous is None:
            res.rank_id = -1
            return res
        rank, size, rdzv_id, coord = self._rendezvous.get_comm_rank(
            request.worker_host
        )
        res.rank_id = rank
        res.world_size = size
        res.rendezvous_id = rdzv_id
        res.coordinator_addr = coord
        return res

    @rpc_error_guard
    def report_train_loop_status(self, request, _context=None):
        if self._rendezvous is not None:
            if request.status == pb.LOOP_START:
                self._rendezvous.add_worker(request.worker_host)
            elif request.status == pb.LOOP_END:
                self._rendezvous.remove_worker(request.worker_host)
        return pb.Empty()

    # -- evaluation / versions ---------------------------------------------

    @rpc_error_guard
    def report_evaluation_metrics(self, request, _context=None):
        if self._evaluation_service is not None:
            outputs = {
                k: tensor_codec.pb_to_ndarray(v)
                for k, v in request.model_outputs.items()
            }
            labels = tensor_codec.pb_to_ndarray(request.labels)
            if len(outputs) == 1:
                outputs = next(iter(outputs.values()))
            self._evaluation_service.report_evaluation_metrics(
                outputs, labels,
                model_version=request.model_version,
            )
        return pb.Empty()

    # A worker whose last telemetry report is older than this is
    # excluded from the JOB aggregate (it is preempted, finished, or
    # mid-outage — summing its stale steps/s would overstate the job),
    # but stays in the per-worker view with its age visible ...
    TELEMETRY_STALE_SECS = 60.0
    # ... until this much older, when the entry is EVICTED outright:
    # a long elastic job churns through ever-new worker ids, and
    # without eviction both the dict and the /status payload grow
    # without bound while exporting hours-dead workers' last values.
    TELEMETRY_EVICT_SECS = 900.0

    def telemetry(self, now=None):
        """Copy-safe per-worker + per-job telemetry aggregate: the
        resize-controller sensor surface (/status "telemetry" section,
        /metrics elasticdl_job_steps_per_sec et al).  Includes the
        percentile plane: per-worker straggler flags + recent step
        p50, and the per-job step-time histogram (exact merge of the
        piggybacked worker deltas)."""
        now = time.time() if now is None else now
        with self._lock:
            dead = [
                worker_id
                for worker_id, t in self.worker_telemetry.items()
                if now - t["ts"] > self.TELEMETRY_EVICT_SECS
            ]
            for worker_id in dead:
                del self.worker_telemetry[worker_id]
                self.worker_step_hist.pop(worker_id, None)
                self._straggler_prev.pop(worker_id, None)
                self._straggler_state.pop(worker_id, None)
            workers = {
                worker_id: dict(t)
                for worker_id, t in self.worker_telemetry.items()
            }
            straggler = {
                worker_id: dict(s)
                for worker_id, s in self._straggler_state.items()
            }
            job_hist = dict(self.job_step_hist,
                            counts=list(self.job_step_hist["counts"]))
        live_rate = 0.0
        reporting = 0
        for worker_id, t in workers.items():
            t["age_secs"] = round(now - t.pop("ts"), 3)
            t["fresh"] = t["age_secs"] <= self.TELEMETRY_STALE_SECS
            if t["fresh"]:
                reporting += 1
                live_rate += t["steps_per_sec"]
            s = straggler.get(worker_id)
            if s is not None:
                t["straggler"] = (
                    s["flagged"] >= self.STRAGGLER_SUSTAIN_SWEEPS
                )
                if s.get("p50_ms") is not None:
                    t["step_p50_ms"] = round(s["p50_ms"], 3)
        job = {
            "steps_per_sec": round(live_rate, 3),
            "workers_reporting": reporting,
        }
        if job_hist["count"] > 0:
            p50 = hist_mod.quantile(job_hist, 0.5)
            p99 = hist_mod.quantile(job_hist, 0.99)
            job["step_hist"] = job_hist
            job["step_time_p50_ms"] = round(1e3 * p50, 3)
            job["step_time_p99_ms"] = round(1e3 * p99, 3)
        return {
            "workers": workers,
            "job": job,
        }

    def rpc_histograms(self):
        """{method: snapshot} of the handled-RPC wall-time histograms
        (_timed_rpc phases, "rpc." prefix stripped for the label)."""
        return {
            name[len("rpc."):]: snap
            for name, snap in self.timing.histograms().items()
            if name.startswith("rpc.")
        }

    # -- straggler detection -------------------------------------------------

    # A worker needs this many step samples in a sweep window to be
    # judged at all (a worker between tasks must not read as "fast"
    # or "slow" off two samples)...
    STRAGGLER_MIN_SAMPLES = 4
    # ... is FLAGGED when its windowed p50 step time exceeds this
    # multiple of the cross-worker median ...
    STRAGGLER_RATIO = 2.0
    # ... and is a sustained STRAGGLER once flagged in this many
    # CONSECUTIVE sweeps (one slow window — a GC pause, a checkpoint —
    # must not trigger policy).
    STRAGGLER_SUSTAIN_SWEEPS = 2

    def straggler_sweep(self, now=None):
        """One detector pass over the per-worker step-time deltas
        since the previous sweep: computes each reporting worker's
        windowed p50, compares against the cross-worker median, and
        updates consecutive-flag counts.  Returns the worker ids that
        are SUSTAINED stragglers right now.  Called at the resize
        controller's cadence (and by tests directly); needs >= 2
        workers with enough samples — skew is relative by definition.

        A newly sustained straggler emits a ``worker.straggler``
        flight-recorder event; policy (deweight / evict) lives in the
        ResizeController, which treats the returned set as preferred
        donors (docs/scheduler.md)."""
        newly = []
        with self._lock:
            p50s = {}
            for worker_id, acc in self.worker_step_hist.items():
                prev = self._straggler_prev.get(worker_id)
                d = hist_mod.delta(acc, prev)
                if d["count"] < self.STRAGGLER_MIN_SAMPLES:
                    # Below the judgement floor: do NOT rotate the
                    # mark — the window keeps accumulating until it
                    # holds enough samples.  (Rotating every sweep
                    # made any worker slower than MIN_SAMPLES/cadence
                    # steps per sweep permanently unjudgeable — and a
                    # straggler is by definition slow.)
                    continue
                self._straggler_prev[worker_id] = dict(
                    acc, counts=list(acc["counts"]))
                window = hist_mod.empty_snapshot()
                hist_mod.merge_delta(window, d)
                p50s[worker_id] = hist_mod.quantile(window, 0.5)
            for worker_id, p50 in p50s.items():
                # LEAVE-ONE-OUT median: each worker is judged against
                # the median of the OTHERS.  A plain all-workers
                # median caps the reachable ratio at 2.0 in a
                # two-worker job (the slow worker drags the median up
                # toward itself), making small jobs' stragglers
                # undetectable by construction.
                others = sorted(p for w, p in p50s.items()
                                if w != worker_id)
                if not others:
                    continue
                mid = len(others) // 2
                median = (others[mid] if len(others) % 2
                          else (others[mid - 1] + others[mid]) / 2.0)
                state = self._straggler_state.setdefault(
                    worker_id, {"flagged": 0, "p50_ms": None,
                                "ratio": None})
                state["p50_ms"] = 1e3 * p50
                if median > 0:
                    state["ratio"] = p50 / median
                    if p50 > self.STRAGGLER_RATIO * median:
                        state["flagged"] += 1
                        if state["flagged"] == (
                                self.STRAGGLER_SUSTAIN_SWEEPS):
                            newly.append(
                                (worker_id, state["ratio"]))
                    else:
                        state["flagged"] = 0
            # Workers that reported nothing this window keep their
            # count (a stalled straggler must not un-flag by going
            # silent — silence is the stale-eviction sweep's job).
            sustained = [
                worker_id
                for worker_id, s in self._straggler_state.items()
                if s["flagged"] >= self.STRAGGLER_SUSTAIN_SWEEPS
            ]
        for worker_id, ratio in newly:
            # Outside the lock: recorder event + log for the newly
            # sustained only (not every sweep re-announces).
            tracing.event("worker.straggler", worker=worker_id,
                          job=self._job_id, ratio=round(ratio, 3))
            logger.warning(
                "worker %d flagged as straggler (windowed p50 %.1fx "
                "the cross-worker median)", worker_id, ratio)
        return sustained

    def stragglers(self):
        """Currently sustained straggler ids (no sweep — the view)."""
        with self._lock:
            return [
                worker_id
                for worker_id, s in self._straggler_state.items()
                if s["flagged"] >= self.STRAGGLER_SUSTAIN_SWEEPS
            ]

    def ps_state(self):
        """Copy-safe snapshot of per-shard PS recovery state for the
        status page."""
        with self._lock:
            return {
                ps_id: dict(s)
                for ps_id, s in self.ps_shard_state.items()
            }

    def ps_commit_mark(self):
        """Cross-shard min of the reported durable versions — an UPPER
        BOUND on the committed checkpoint label a restore would come
        back at.  Exact in the common case (every shard saves every
        cadence label); it can overstate when a shard skipped a label
        (``ps_ckpt_failed`` > 0 on any shard is the signal — the true
        committed label may then be older than this mark, the disk is
        authoritative) or before every shard has reported.  None until
        a PS shard has reported.  The gap between ``model_version`` and
        this mark is at least the state a crash right now would lose."""
        with self._lock:
            if not self.ps_shard_state:
                return None
            return min(
                s["durable_version"]
                for s in self.ps_shard_state.values()
            )

    @rpc_error_guard
    def report_version(self, request, _context=None):
        shard_restarted = False
        with self._lock:
            advanced = request.model_version > self._version
            self._version = max(self._version, request.model_version)
            if request.is_ps:
                state = self.ps_shard_state.setdefault(
                    request.ps_id,
                    {"generation": 0, "version": 0,
                     "durable_version": 0},
                )
                # A report from an OLDER incarnation (delayed by its
                # client's outage-riding retry, landing after the
                # relaunch already reported) must not touch the
                # recovery state: its durable_version describes files
                # the restore-time truncation may have deleted, and
                # folding it in would float the commit mark above what
                # is actually on disk.
                if request.generation >= state["generation"]:
                    if state["generation"] and (
                        request.generation > state["generation"]
                    ):
                        shard_restarted = True
                        logger.warning(
                            "PS shard %d serving as generation %d "
                            "(was %d): shard restarted",
                            request.ps_id, request.generation,
                            state["generation"],
                        )
                    state["generation"] = request.generation
                    state["version"] = max(
                        state["version"], request.model_version
                    )
                    # NOT max-folded: a relaunched shard that restored
                    # an older committed version really is durable only
                    # up to there — the mark must move back with it.
                    state["durable_version"] = request.durable_version
        if shard_restarted:
            # In the reporting shard's trace: the restart-generation
            # bump as the master observed it.
            tracing.event("ps.generation_bump", ps_id=request.ps_id,
                          generation=request.generation,
                          durable_version=request.durable_version)
        if advanced and self._journal is not None:
            self._journal.append(
                {"ev": "version", "v": request.model_version}
            )
        if self._evaluation_service is not None:
            self._evaluation_service.add_evaluation_task_if_needed(
                request.model_version
            )
        return pb.Empty()

    @rpc_error_guard
    def report_training_params(self, request, _context=None):
        self.training_params = request
        return pb.Empty()


def create_master_service(servicer, port=0, max_workers=64,
                          interceptors=None):
    """Start an in-process gRPC master service; returns (server, port).

    ``interceptors``: e.g. a grpc_utils.FaultInjectionInterceptor —
    drills script deterministic master outages with --rpc_fault_spec."""
    server = grpc_utils.build_server(
        max_workers=max_workers, interceptors=interceptors
    )
    rpc.add_master_servicer(servicer, server)
    bound = server.add_insecure_port("[::]:%d" % port)
    server.start()
    logger.info("master service listening on port %d", bound)
    return server, bound
