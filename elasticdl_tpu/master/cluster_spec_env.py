"""Foreign-runtime cluster-spec env emission.

The reference's elastic master can host a foreign framework's OWN
distribution protocol by writing a ``TF_CONFIG`` env — the cluster's
worker/ps host list plus this pod's task identity — into every pod it
launches (elasticdl/python/master/pod_manager.py:405-422).  The master
only schedules and relaunches; the foreign runtime speaks its own
protocol between the addresses.

This is that capability as the ~20-line hook PARITY.md promises: build
the env dict here, hand it to ``WorkerManager(cluster_env_fn=...)``,
and every launch (including relaunches) carries it.  The task index is
the worker's stable SLOT, not its ever-increasing worker id, so a
replacement pod inherits the identity its predecessors held — exactly
how the reference keeps a TF cluster spec valid across relaunches
(slot services re-point at the replacement pod).
"""

import json


def tf_config_env(worker_hosts, ps_hosts=None, task_type="worker",
                  task_index=0, chief_hosts=None):
    """{env_name: value} for one task of a TF_CONFIG-shaped cluster."""
    cluster = {"worker": list(worker_hosts)}
    if ps_hosts:
        cluster["ps"] = list(ps_hosts)
    if chief_hosts:
        cluster["chief"] = list(chief_hosts)
    return {
        "TF_CONFIG": json.dumps({
            "cluster": cluster,
            "task": {"type": task_type, "index": int(task_index)},
        })
    }


def make_tf_config_fn(worker_hosts, ps_hosts=None):
    """A ``WorkerManager`` ``cluster_env_fn``: (worker_id, slot) ->
    env.  The slot indexes into ``worker_hosts`` (slot addresses are
    stable across relaunches — k8s slot services, or fixed host:port
    assignments for process workers)."""

    def cluster_env_fn(worker_id, slot):
        del worker_id  # identity follows the slot, not the launch count
        return tf_config_env(
            worker_hosts, ps_hosts=ps_hosts,
            task_type="worker", task_index=slot,
        )

    return cluster_env_fn
