"""Dynamic data sharding — the heart of elasticity.

The TaskManager partitions the dataset into shards and hands them out as
tasks; any task owned by a dead worker goes back on the todo queue, which is
what lets workers die and join freely.  Semantics match the reference's
task manager (elasticdl/python/master/task_manager.py:35-616): todo/doing
queues, <=3 retries per task, per-epoch regeneration with optional shuffle,
a timeout watchdog, version-triggered evaluation tasks and a deferred
train-end callback task.
"""

import random
import threading
import time
from collections import deque, namedtuple

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

MAX_TASK_RETRIES = 3
TASK_TIMEOUT_THRESHOLD_SECS = 300

# Result of TaskManager.report: task is None for unknown ids;
# permanent_failure marks a task that exhausted its retries.
ReportResult = namedtuple("ReportResult", ["ok", "task", "permanent_failure"])


class Shard:
    __slots__ = ("name", "start", "end", "record_indices")

    def __init__(self, name, start, end, record_indices=None):
        self.name = name
        self.start = start
        self.end = end
        self.record_indices = record_indices or []

    @property
    def size(self):
        return self.end - self.start

    def to_pb(self, out=None):
        s = out if out is not None else pb.ShardPB()
        s.name = self.name
        s.start = self.start
        s.end = self.end
        del s.record_indices[:]
        s.record_indices.extend(self.record_indices)
        return s


class Task:
    __slots__ = ("id", "shard", "type", "model_version", "retry_count")

    def __init__(self, task_id, shard, task_type, model_version=-1):
        self.id = task_id
        self.shard = shard
        self.type = task_type
        self.model_version = model_version
        self.retry_count = 0

    def to_pb(self, out=None):
        t = out if out is not None else pb.TaskPB()
        t.id = self.id
        t.type = self.type
        self.shard.to_pb(out=t.shard)
        t.model_version = self.model_version
        return t


def wait_task_pb():
    return pb.TaskPB(id=-1, type=pb.WAIT)


class TaskManager:
    """Thread-safe todo/doing task queues over dataset shards."""

    def __init__(
        self,
        training_shards=None,
        evaluation_shards=None,
        prediction_shards=None,
        records_per_task=None,
        num_epochs=1,
        shuffle=False,
        shuffle_shards=False,
        max_task_retries=MAX_TASK_RETRIES,
        task_timeout_secs=TASK_TIMEOUT_THRESHOLD_SECS,
        seed=None,
    ):
        self._lock = threading.Lock()
        self._training_shards = list(training_shards or [])
        self._evaluation_shards = list(evaluation_shards or [])
        self._prediction_shards = list(prediction_shards or [])
        self._records_per_task = records_per_task
        self._num_epochs = num_epochs
        self._shuffle = shuffle
        self._shuffle_shards = shuffle_shards
        self._max_task_retries = max_task_retries
        self._task_timeout_secs = task_timeout_secs
        self._rng = random.Random(seed)

        self._todo = deque()
        # task_id -> (worker_id, task, start_time)
        self._doing = {}
        self._task_id = 0
        self._epoch = 0
        self._train_end_callback_pending = False
        self._train_end_callback_done = False
        self._max_task_completed_time = 0.0
        self.completed_counts = {t: 0 for t in
                                 (pb.TRAINING, pb.EVALUATION, pb.PREDICTION,
                                  pb.TRAIN_END_CALLBACK)}
        self.failed_counts = dict(self.completed_counts)
        self._worker_timeout_callbacks = []
        self._watchdog = None
        self._stopped = threading.Event()

        if self._training_shards:
            logger.info(
                "TaskManager: %d training shards, %d epochs",
                len(self._training_shards), num_epochs,
            )
            self._create_training_tasks_locked()
        elif self._prediction_shards:
            self._create_tasks_locked(self._prediction_shards, pb.PREDICTION)

    # -- task creation ------------------------------------------------------

    def _split(self, shards):
        """Split (name, start, end) ranges into records_per_task chunks."""
        out = []
        for name, start, end in shards:
            if not self._records_per_task:
                out.append(Shard(name, start, end))
                continue
            pos = start
            while pos < end:
                chunk_end = min(pos + self._records_per_task, end)
                out.append(Shard(name, pos, chunk_end))
                pos = chunk_end
        return out

    def _create_tasks_locked(self, shards, task_type, model_version=-1):
        pieces = self._split(shards)
        if task_type == pb.TRAINING and self._shuffle_shards:
            self._rng.shuffle(pieces)
        if task_type == pb.TRAINING and self._shuffle:
            for piece in pieces:
                indices = list(range(piece.start, piece.end))
                self._rng.shuffle(indices)
                piece.record_indices = indices
        tasks = []
        for piece in pieces:
            self._task_id += 1
            tasks.append(Task(self._task_id, piece, task_type, model_version))
        self._todo.extend(tasks)
        return tasks

    def _create_training_tasks_locked(self):
        self._create_tasks_locked(self._training_shards, pb.TRAINING)

    def skip_records(self, num_records):
        """Drop already-trained records after a checkpoint resume
        (reference: master recovers completed_steps from the checkpoint
        version, task_manager.py:208-221).  Whole tasks are dropped while
        their full span fits in num_records; the remainder trims the next
        task's front."""
        with self._lock:
            skipped = 0
            while self._todo and num_records - skipped > 0:
                task = self._todo[0]
                size = task.shard.size
                if size <= num_records - skipped:
                    self._todo.popleft()
                    skipped += size
                    self.completed_counts[task.type] += 1
                else:
                    trim = num_records - skipped
                    task.shard.start += trim
                    if task.shard.record_indices:
                        task.shard.record_indices = (
                            task.shard.record_indices[trim:]
                        )
                    skipped += trim
            logger.info("resume: skipped %d records", skipped)
            return skipped

    def create_evaluation_tasks(self, model_version):
        """Version-triggered eval job (reference task_manager create_evaluation_tasks)."""
        with self._lock:
            tasks = self._create_tasks_locked(
                self._evaluation_shards, pb.EVALUATION, model_version
            )
            # Evaluation interleaves ahead of remaining training tasks.
            for _ in tasks:
                self._todo.rotate(1)
            return len(tasks)

    def set_train_end_callback_task(self):
        with self._lock:
            self._train_end_callback_pending = True

    # -- dispatch -----------------------------------------------------------

    def get(self, worker_id):
        """Pop the next task for a worker; None when nothing is available."""
        with self._lock:
            if not self._todo and not self._doing:
                if self._epoch < self._num_epochs - 1 and self._training_shards:
                    self._epoch += 1
                    logger.info("starting epoch %d", self._epoch)
                    self._create_training_tasks_locked()
                elif (
                    self._train_end_callback_pending
                    and not self._train_end_callback_done
                    and self._finished_training_locked()
                ):
                    self._train_end_callback_done = True
                    self._task_id += 1
                    task = Task(
                        self._task_id, Shard("", 0, 0), pb.TRAIN_END_CALLBACK
                    )
                    self._doing[task.id] = (worker_id, task, time.time())
                    return task
            if not self._todo:
                return None
            task = self._todo.popleft()
            self._doing[task.id] = (worker_id, task, time.time())
            return task

    def report(self, task_id, success, err_message="", requeue=False):
        """Worker reports a task result; failed tasks are retried <=N times.

        ``requeue=True`` (an explicit proto field, set by observers like
        the job monitor) puts the task back WITHOUT consuming a retry
        and without counting completion — the task was only peeked,
        never worked.

        Returns a ReportResult.
        """
        with self._lock:
            entry = self._doing.pop(task_id, None)
            if entry is None:
                logger.warning("report for unknown task %d", task_id)
                return ReportResult(False, None, False)
            worker_id, task, start_time = entry
            if requeue:
                logger.info("task %d handed back by observer", task_id)
                self._todo.appendleft(task)
                return ReportResult(False, task, False)
            if success:
                elapsed = time.time() - start_time
                self._max_task_completed_time = max(
                    self._max_task_completed_time, elapsed
                )
                self.completed_counts[task.type] += 1
                return ReportResult(True, task, False)
            task.retry_count += 1
            if task.retry_count <= self._max_task_retries:
                logger.info(
                    "task %d failed (%s), retry %d/%d",
                    task_id, err_message, task.retry_count,
                    self._max_task_retries,
                )
                self._todo.appendleft(task)
                return ReportResult(False, task, False)
            logger.error(
                "task %d permanently failed: %s", task_id, err_message
            )
            self.failed_counts[task.type] += 1
            return ReportResult(False, task, True)

    def recover_tasks(self, worker_id):
        """Re-queue every task a dead worker was holding (elasticity path)."""
        with self._lock:
            owned = [
                tid for tid, (wid, _, _) in self._doing.items()
                if wid == worker_id
            ]
        for tid in owned:
            self.report(tid, False, err_message="worker %s died" % worker_id)

    # -- progress -----------------------------------------------------------

    def _finished_training_locked(self):
        done_epochs = self._epoch >= self._num_epochs - 1
        return done_epochs and not self._todo and not any(
            t.type == pb.TRAINING for _, t, _ in self._doing.values()
        )

    def finished_training(self):
        with self._lock:
            return self._finished_training_locked()

    def finished(self):
        with self._lock:
            more_epochs = (
                self._training_shards and self._epoch < self._num_epochs - 1
            )
            pending_callback = (
                self._train_end_callback_pending
                and not self._train_end_callback_done
            )
            return (
                not self._todo
                and not self._doing
                and not more_epochs
                and not pending_callback
            )

    def counts(self):
        with self._lock:
            return {
                "todo": len(self._todo),
                "doing": len(self._doing),
                "completed": dict(self.completed_counts),
                "failed": dict(self.failed_counts),
                "epoch": self._epoch,
            }

    # -- timeout watchdog ---------------------------------------------------

    def add_worker_timeout_callback(self, fn):
        """fn(worker_id) called when a worker times out on a task."""
        self._worker_timeout_callbacks.append(fn)

    def start(self):
        self._watchdog = threading.Thread(
            target=self._watch_timeouts, name="task-timeout-watchdog",
            daemon=True,
        )
        self._watchdog.start()

    def stop(self):
        self._stopped.set()

    def _timeout_threshold(self):
        with self._lock:
            longest = self._max_task_completed_time
        return max(self._task_timeout_secs, 3 * longest)

    def _watch_timeouts(self):
        while not self._stopped.wait(timeout=5):
            threshold = self._timeout_threshold()
            now = time.time()
            with self._lock:
                timed_out = [
                    (tid, wid) for tid, (wid, _, start) in self._doing.items()
                    if now - start > threshold
                ]
            for tid, wid in timed_out:
                logger.warning(
                    "task %d timed out on worker %s; re-queueing", tid, wid
                )
                self.report(tid, False, err_message="timeout")
                for fn in self._worker_timeout_callbacks:
                    fn(wid)
