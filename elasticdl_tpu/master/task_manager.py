"""Dynamic data sharding — the heart of elasticity.

The TaskManager partitions the dataset into shards and hands them out as
tasks; any task owned by a dead worker goes back on the todo queue, which is
what lets workers die and join freely.  Semantics match the reference's
task manager (elasticdl/python/master/task_manager.py:35-616): todo/doing
queues, <=3 retries per task, per-epoch regeneration with optional shuffle,
a timeout watchdog, version-triggered evaluation tasks and a deferred
train-end callback task.
"""

import random
import threading
import time
from collections import deque, namedtuple

from elasticdl_tpu.master.journal import journal_events
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

MAX_TASK_RETRIES = 3
TASK_TIMEOUT_THRESHOLD_SECS = 300

# Result of TaskManager.report: task is None for unknown ids;
# permanent_failure marks a task that exhausted its retries.
ReportResult = namedtuple("ReportResult", ["ok", "task", "permanent_failure"])


class Shard:
    __slots__ = ("name", "start", "end", "record_indices")

    def __init__(self, name, start, end, record_indices=None):
        self.name = name
        self.start = start
        self.end = end
        self.record_indices = record_indices or []

    @property
    def size(self):
        return self.end - self.start

    def to_pb(self, out=None):
        s = out if out is not None else pb.ShardPB()
        s.name = self.name
        s.start = self.start
        s.end = self.end
        del s.record_indices[:]
        s.record_indices.extend(self.record_indices)
        return s


class Task:
    __slots__ = ("id", "shard", "type", "model_version", "retry_count")

    def __init__(self, task_id, shard, task_type, model_version=-1):
        self.id = task_id
        self.shard = shard
        self.type = task_type
        self.model_version = model_version
        self.retry_count = 0

    def to_pb(self, out=None):
        t = out if out is not None else pb.TaskPB()
        t.id = self.id
        t.type = self.type
        self.shard.to_pb(out=t.shard)
        t.model_version = self.model_version
        return t


def wait_task_pb():
    return pb.TaskPB(id=-1, type=pb.WAIT)


class TaskManager:
    """Thread-safe todo/doing task queues over dataset shards."""

    def __init__(
        self,
        training_shards=None,
        evaluation_shards=None,
        prediction_shards=None,
        records_per_task=None,
        num_epochs=1,
        shuffle=False,
        shuffle_shards=False,
        max_task_retries=MAX_TASK_RETRIES,
        task_timeout_secs=TASK_TIMEOUT_THRESHOLD_SECS,
        seed=None,
    ):
        self._lock = threading.Lock()
        self._training_shards = list(training_shards or [])
        self._evaluation_shards = list(evaluation_shards or [])
        self._prediction_shards = list(prediction_shards or [])
        self._records_per_task = records_per_task
        self._num_epochs = num_epochs
        self._shuffle = shuffle
        self._shuffle_shards = shuffle_shards
        self._max_task_retries = max_task_retries
        self._task_timeout_secs = task_timeout_secs
        self._rng = random.Random(seed)

        self._todo = deque()
        # task_id -> (worker_id, task, start_time)
        self._doing = {}
        self._task_id = 0
        self._epoch = 0
        # Crash-restart recovery (master/journal.py): lifecycle events
        # stream to the journal (appended OUTSIDE self._lock — EL006
        # proves it); _done_ids lets a restarted master deduplicate a
        # worker re-reporting a task the pre-crash master already
        # counted, so nothing is double-counted across a restart.
        self._journal = None
        self._done_ids = set()
        self._train_end_callback_pending = False
        self._train_end_callback_done = False
        self._max_task_completed_time = 0.0
        self.completed_counts = {t: 0 for t in
                                 (pb.TRAINING, pb.EVALUATION, pb.PREDICTION,
                                  pb.TRAIN_END_CALLBACK)}
        self.failed_counts = dict(self.completed_counts)
        self._worker_timeout_callbacks = []
        self._watchdog = None
        self._stopped = threading.Event()

        if self._training_shards:
            logger.info(
                "TaskManager: %d training shards, %d epochs",
                len(self._training_shards), num_epochs,
            )
            self._create_training_tasks_locked()
        elif self._prediction_shards:
            self._create_tasks_locked(self._prediction_shards, pb.PREDICTION)

    # -- task creation ------------------------------------------------------

    def _split(self, shards):
        """Split (name, start, end) ranges into records_per_task chunks."""
        out = []
        for name, start, end in shards:
            if not self._records_per_task:
                out.append(Shard(name, start, end))
                continue
            pos = start
            while pos < end:
                chunk_end = min(pos + self._records_per_task, end)
                out.append(Shard(name, pos, chunk_end))
                pos = chunk_end
        return out

    @staticmethod
    def _task_event(task):
        event = {
            "ev": "task", "id": task.id, "type": task.type,
            "name": task.shard.name, "start": task.shard.start,
            "end": task.shard.end, "mv": task.model_version,
        }
        if task.shard.record_indices:
            event["idx"] = list(task.shard.record_indices)
        return event

    def _create_tasks_locked(self, shards, task_type, model_version=-1,
                             events=None):
        pieces = self._split(shards)
        if task_type == pb.TRAINING and self._shuffle_shards:
            self._rng.shuffle(pieces)
        if task_type == pb.TRAINING and self._shuffle:
            for piece in pieces:
                indices = list(range(piece.start, piece.end))
                self._rng.shuffle(indices)
                piece.record_indices = indices
        tasks = []
        for piece in pieces:
            self._task_id += 1
            tasks.append(Task(self._task_id, piece, task_type, model_version))
        self._todo.extend(tasks)
        if events is not None:
            events.extend(self._task_event(t) for t in tasks)
        return tasks

    def _create_training_tasks_locked(self, events=None):
        self._create_tasks_locked(
            self._training_shards, pb.TRAINING, events=events
        )

    def skip_records(self, num_records):
        """Drop already-trained records after a checkpoint resume
        (reference: master recovers completed_steps from the checkpoint
        version, task_manager.py:208-221).  Whole tasks are dropped while
        their full span fits in num_records; the remainder trims the next
        task's front."""
        events = []
        with self._lock:
            skipped = 0
            while self._todo and num_records - skipped > 0:
                task = self._todo[0]
                size = task.shard.size
                if size <= num_records - skipped:
                    self._todo.popleft()
                    skipped += size
                    self.completed_counts[task.type] += 1
                    self._done_ids.add(task.id)
                    events.append({"ev": "done", "id": task.id})
                else:
                    trim = num_records - skipped
                    task.shard.start += trim
                    if task.shard.record_indices:
                        task.shard.record_indices = (
                            task.shard.record_indices[trim:]
                        )
                    skipped += trim
                    events.append(
                        {"ev": "trim", "id": task.id,
                         "start": task.shard.start}
                    )
            logger.info("resume: skipped %d records", skipped)
        journal_events(self._journal, events)
        return skipped

    def create_evaluation_tasks(self, model_version):
        """Version-triggered eval job (reference task_manager create_evaluation_tasks)."""
        events = []
        with self._lock:
            tasks = self._create_tasks_locked(
                self._evaluation_shards, pb.EVALUATION, model_version,
                events=events,
            )
            # Evaluation interleaves ahead of remaining training tasks.
            for _ in tasks:
                self._todo.rotate(1)
            n = len(tasks)
        journal_events(self._journal, events)
        return n

    def set_train_end_callback_task(self):
        with self._lock:
            self._train_end_callback_pending = True
        journal_events(self._journal, [{"ev": "cb"}])

    # -- dispatch -----------------------------------------------------------

    def get(self, worker_id):
        """Pop the next task for a worker; None when nothing is available."""
        events = []
        with self._lock:
            task = self._get_locked(worker_id, events)
        journal_events(self._journal, events)
        return task

    def _get_locked(self, worker_id, events):
        if not self._todo and not self._doing:
            if self._epoch < self._num_epochs - 1 and self._training_shards:
                self._epoch += 1
                logger.info("starting epoch %d", self._epoch)
                events.append({"ev": "epoch", "n": self._epoch})
                self._create_training_tasks_locked(events=events)
            elif (
                self._train_end_callback_pending
                and not self._train_end_callback_done
                and self._finished_training_locked()
            ):
                self._train_end_callback_done = True
                self._task_id += 1
                task = Task(
                    self._task_id, Shard("", 0, 0), pb.TRAIN_END_CALLBACK
                )
                self._doing[task.id] = (worker_id, task, time.time())
                events.append(self._task_event(task))
                events.append(
                    {"ev": "dispatch", "id": task.id, "w": worker_id}
                )
                return task
        if not self._todo:
            return None
        task = self._todo.popleft()
        self._doing[task.id] = (worker_id, task, time.time())
        events.append({"ev": "dispatch", "id": task.id, "w": worker_id})
        return task

    def report(self, task_id, success, err_message="", requeue=False):
        """Worker reports a task result; failed tasks are retried <=N times.

        ``requeue=True`` (an explicit proto field, set by observers like
        the job monitor) puts the task back WITHOUT consuming a retry
        and without counting completion — the task was only peeked,
        never worked.

        Replay safety across a master restart: a report for a task the
        journaled master already completed is deduplicated (idempotent
        success), and a success report for a task sitting in the todo
        queue (requeued on restart while its worker rode out the
        outage) completes it from the queue — the task is neither
        double-counted nor re-trained.

        Returns a ReportResult.
        """
        events = []
        with self._lock:
            result = self._report_locked(
                task_id, success, err_message, requeue, events
            )
        journal_events(self._journal, events)
        # Mirror the task-lifecycle journal events into the flight
        # recorder (same outside-the-lock discipline): a task put BACK
        # in the queue — retry or requeue — is the elastic incident a
        # trace wants, and it lands in the reporting worker's trace
        # (servicer records the completion-side breadcrumbs).
        for ev in events:
            if ev.get("ev") == "requeue":
                tracing.event("task.requeue", task=ev.get("id"))
        return result

    def _report_locked(self, task_id, success, err_message, requeue,
                       events):
        entry = self._doing.pop(task_id, None)
        if entry is None:
            return self._report_undispatched_locked(
                task_id, success, err_message, requeue, events
            )
        worker_id, task, start_time = entry
        if requeue:
            logger.info("task %d handed back by observer", task_id)
            self._todo.appendleft(task)
            events.append({"ev": "requeue", "id": task_id})
            return ReportResult(False, task, False)
        if success:
            elapsed = time.time() - start_time
            self._max_task_completed_time = max(
                self._max_task_completed_time, elapsed
            )
            return self._complete_locked(task, events)
        return self._fail_locked(task, err_message, events)

    def _report_undispatched_locked(self, task_id, success, err_message,
                                    requeue, events):
        """A report for a task not in doing: either a duplicate of an
        already-counted completion (master restarted after journaling
        it) or a task the restart requeued while its worker kept
        working through the outage."""
        if task_id in self._done_ids:
            logger.info(
                "task %d already completed; duplicate report "
                "deduplicated", task_id,
            )
            return ReportResult(True, None, False)
        task = next(
            (t for t in self._todo if t.id == task_id), None
        )
        if task is None:
            logger.warning("report for unknown task %d", task_id)
            return ReportResult(False, None, False)
        if requeue:
            # Observer hand-back (e.g. graceful preemption) racing the
            # restart's own requeue: the task is already back in todo —
            # leave it there, and honor the no-retry-burned contract.
            logger.info(
                "task %d handed back by observer; already requeued",
                task_id,
            )
            return ReportResult(False, task, False)
        if success:
            self._todo.remove(task)
            logger.info(
                "task %d completed by a worker that rode out a master "
                "restart; accepting from the requeued state", task_id,
            )
            return self._complete_locked(task, events)
        # Failure report for a task sitting in todo: it is ALREADY
        # queued for re-dispatch, so requeue is the right outcome and
        # it has happened.  Do not burn a retry — under the client's
        # RPC retry a processed-failure-with-lost-response is reported
        # twice, and charging both would permanently fail a task after
        # half its real budget.  A genuinely poisoned task still burns
        # retries normally once re-dispatched (it fails from _doing).
        logger.info(
            "task %d failure reported (%s) while already requeued; "
            "keeping queued without charging a retry",
            task_id, err_message or "unspecified",
        )
        return ReportResult(False, task, False)

    def _complete_locked(self, task, events):
        self.completed_counts[task.type] += 1
        self._done_ids.add(task.id)
        events.append({"ev": "done", "id": task.id})
        return ReportResult(True, task, False)

    def _fail_locked(self, task, err_message, events):
        task.retry_count += 1
        if task.retry_count <= self._max_task_retries:
            logger.info(
                "task %d failed (%s), retry %d/%d",
                task.id, err_message, task.retry_count,
                self._max_task_retries,
            )
            self._todo.appendleft(task)
            events.append(
                {"ev": "fail", "id": task.id, "perm": False,
                 "retries": task.retry_count}
            )
            return ReportResult(False, task, False)
        logger.error(
            "task %d permanently failed: %s", task.id, err_message
        )
        self.failed_counts[task.type] += 1
        events.append(
            {"ev": "fail", "id": task.id, "perm": True,
             "retries": task.retry_count}
        )
        return ReportResult(False, task, True)

    # -- crash-restart recovery (master/journal.py) -------------------------

    def attach_journal(self, journal, bootstrap=True):
        """Start streaming lifecycle events to ``journal``.

        ``bootstrap=True`` (fresh start) journals the current queue so
        replay can rebuild it; a restarted master attaches with
        ``bootstrap=False`` — its state CAME from the journal, and
        re-journaling it would duplicate every task record."""
        events = []
        if bootstrap:
            with self._lock:
                if self._epoch:
                    events.append({"ev": "epoch", "n": self._epoch})
                events.extend(self._task_event(t) for t in self._todo)
                if self._train_end_callback_pending:
                    events.append({"ev": "cb"})
        self._journal = journal
        journal_events(journal, events)

    def restore_from_journal(self, state):
        """Rebuild the queues from a replayed JournalState: in-flight
        tasks are requeued at the front (their worker may be mid-task,
        riding out the outage — `report` accepts their result from the
        queue), completed/failed counts and the epoch resume exactly,
        and already-completed ids arm the duplicate-report dedup."""
        with self._lock:
            self._todo.clear()
            self._doing.clear()
            dropped_eval = 0
            for rec in state.pending_tasks():
                if rec["type"] == pb.EVALUATION:
                    # EvaluationService state (the job's metric
                    # accumulators, completion count) is NOT journaled
                    # — declared out of recovery scope — so a restart
                    # has no eval job to fold these into: completions
                    # would be dropped or, worse, folded into the NEXT
                    # version's job.  Drop them loudly; evaluation
                    # re-arms cleanly at the next version report.
                    dropped_eval += 1
                    continue
                shard = Shard(
                    rec.get("name", ""), rec["start"], rec["end"],
                    list(rec.get("idx") or []),
                )
                task = Task(
                    rec["id"], shard, rec["type"], rec.get("mv", -1)
                )
                task.retry_count = state.retries.get(rec["id"], 0)
                self._todo.append(task)
            if dropped_eval:
                logger.warning(
                    "master restart: %d pending EVALUATION task(s) "
                    "dropped (evaluation-service state is not "
                    "recovered; the next version report re-creates "
                    "the eval job)", dropped_eval,
                )
            self._task_id = max(self._task_id, state.max_task_id)
            self._epoch = max(self._epoch, state.epoch)
            for task_type, n in state.completed_counts.items():
                self.completed_counts[task_type] = n
            for task_type, n in state.failed_counts.items():
                self.failed_counts[task_type] = n
            self._train_end_callback_pending = (
                self._train_end_callback_pending
                or state.train_end_pending
            )
            self._train_end_callback_done = state.train_end_created
            self._done_ids = set(state.done_ids)
            restored = {
                "todo": len(self._todo),
                "completed": dict(self.completed_counts),
                "failed": dict(self.failed_counts),
                "epoch": self._epoch,
                "next_task_id": self._task_id + 1,
            }
        logger.warning(
            "master restart: task state restored from journal "
            "(in-flight tasks requeued): %s", restored,
        )

    def recover_tasks(self, worker_id):
        """Re-queue every task a dead worker was holding (elasticity path)."""
        with self._lock:
            owned = [
                tid for tid, (wid, _, _) in self._doing.items()
                if wid == worker_id
            ]
        for tid in owned:
            self.report(tid, False, err_message="worker %s died" % worker_id)

    def requeue_worker_tasks(self, worker_id):
        """Scheduler drain (docs/scheduler.md): hand back every task the
        worker holds WITHOUT consuming retries — an elastic shrink is
        not the task's fault, exactly like the observer hand-back on
        graceful preemption.  The worker may still be mid-task, riding
        out the re-assignment: when it later reports the requeued task,
        ``report`` accepts the result from the todo queue (the same
        replay-safe path a master restart uses).  Returns the ids."""
        with self._lock:
            owned = [
                tid for tid, (wid, _, _) in self._doing.items()
                if wid == worker_id
            ]
        for tid in owned:
            self.report(
                tid, False,
                err_message="worker %s drained by scheduler" % worker_id,
                requeue=True,
            )
        return owned

    # -- progress -----------------------------------------------------------

    def _finished_training_locked(self):
        done_epochs = self._epoch >= self._num_epochs - 1
        return done_epochs and not self._todo and not any(
            t.type == pb.TRAINING for _, t, _ in self._doing.values()
        )

    def finished_training(self):
        with self._lock:
            return self._finished_training_locked()

    def finished(self):
        with self._lock:
            more_epochs = (
                self._training_shards and self._epoch < self._num_epochs - 1
            )
            pending_callback = (
                self._train_end_callback_pending
                and not self._train_end_callback_done
            )
            return (
                not self._todo
                and not self._doing
                and not more_epochs
                and not pending_callback
            )

    def counts(self):
        with self._lock:
            return {
                "todo": len(self._todo),
                "doing": len(self._doing),
                "completed": dict(self.completed_counts),
                "failed": dict(self.failed_counts),
                "epoch": self._epoch,
            }

    # -- timeout watchdog ---------------------------------------------------

    def add_worker_timeout_callback(self, fn):
        """fn(worker_id) called when a worker times out on a task."""
        self._worker_timeout_callbacks.append(fn)

    def start(self):
        self._watchdog = threading.Thread(
            target=self._watch_timeouts, name="task-timeout-watchdog",
            daemon=True,
        )
        self._watchdog.start()

    def stop(self):
        self._stopped.set()

    def _timeout_threshold(self):
        with self._lock:
            longest = self._max_task_completed_time
        return max(self._task_timeout_secs, 3 * longest)

    def _watch_timeouts(self):
        while not self._stopped.wait(timeout=5):
            threshold = self._timeout_threshold()
            now = time.time()
            with self._lock:
                timed_out = [
                    (tid, wid) for tid, (wid, _, start) in self._doing.items()
                    if now - start > threshold
                ]
            for tid, wid in timed_out:
                logger.warning(
                    "task %d timed out on worker %s; re-queueing", tid, wid
                )
                self.report(tid, False, err_message="timeout")
                for fn in self._worker_timeout_callbacks:
                    fn(wid)
