"""Standalone job monitor (parity:
elasticdl/python/common/k8s_job_monitor.py:32-100): polls a running
master's control plane and summarizes job health without joining it."""

import time

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.retry import RetryPolicy
from elasticdl_tpu.worker.master_client import MasterClient

logger = get_logger(__name__)


class JobMonitor:
    def __init__(self, master_addr, poll_secs=10):
        channel = grpc_utils.build_channel(master_addr)
        # FAIL-FAST policy, not the 120 s outage-riding default: this
        # client's job is to DETECT an unreachable master — riding the
        # outage would stall every probe for the full retry budget and
        # stretch watch()'s unreachability verdict by minutes.
        self._mc = MasterClient(
            channel, worker_id=-2,
            retry=RetryPolicy(
                name="job_monitor", max_attempts=2,
                deadline_secs=2.0, base_delay_secs=0.2,
                max_delay_secs=0.5,
            ),
        )
        self._poll_secs = poll_secs

    def snapshot(self):
        """One health probe: can the master be reached, what world is
        committed, is work still being dispatched."""
        out = {"reachable": False}
        try:
            rank = self._mc.get_comm_rank()
            out["reachable"] = True
            out["world_size"] = rank.world_size
            out["rendezvous_id"] = rank.rendezvous_id
            task = self._mc.get_task(pb.EVALUATION)
            # monitors only peek: hand real work straight back via the
            # explicit requeue field so the probe never consumes a retry
            # or counts as completion
            if task.id > 0:
                self._mc.report_task_result(
                    task.id, err_message="job-monitor probe",
                    requeue=True,
                )
            out["dispatching"] = task.id > 0 or task.type == pb.WAIT
        except Exception as e:  # noqa: BLE001
            out["error"] = str(e)
        return out

    def watch(self, until_unreachable_polls=3):
        misses = 0
        while misses < until_unreachable_polls:
            snap = self.snapshot()
            logger.info("job status: %s", snap)
            misses = 0 if snap["reachable"] else misses + 1
            time.sleep(self._poll_secs)
        logger.info("master unreachable; job presumed finished")
