"""Kubernetes / TPU-VM worker backend.

Plugs into WorkerManager behind the same launch/wait/kill/is_alive surface
as ProcessWorkerBackend (parity with the reference's pod manager + k8s
client, elasticdl/python/master/pod_manager.py:207-674 and
common/k8s_client.py:41-334).  Everything cluster-specific stays in this
one module so the rest of the control plane is backend-agnostic.

Manifests are plain dicts (the k8s API accepts them directly), so the
backend is unit-testable against a fake API object with no ``kubernetes``
package in the image — pass ``core_api=`` to inject one; the default
constructor imports the real client and loads in-cluster/kubeconfig
credentials.

Reference behaviors carried over:
 - pod labels job-name / replica-type / replica-index
   (elasticdl_client/common/k8s_client.py:29-32);
 - a service per worker, patched to select the replacement pod when a
   worker is relaunched under a fresh id
   (common/k8s_client.py:261-274) — so PS/master addressing of a worker
   slot survives relaunches;
 - high/low worker pod priority split: the first
   ``ceil(fraction * num_workers)`` workers get the high priority class
   (pod_manager.py:80-99), protecting a core of the fleet from
   preemption;
 - a pluggable cluster-spec hook: a dotted module path exporting
   ``patch_pod(manifest) -> manifest`` / ``patch_service(manifest) ->
   manifest`` applied before every create, for site-specific tweaks
   (elasticdl_client/common/k8s_client.py:106-218).

Preemption shows up as pod DELETED/gone states, which ``wait`` maps to
the same EV_PREEMPTED flow the process backend uses — so TPU-VM
preemption drills and local kill -9 drills exercise one code path.
"""

import importlib
import math
import threading

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

LABEL_JOB = "elasticdl-tpu-job-name"
LABEL_TYPE = "replica-type"
LABEL_INDEX = "replica-index"


def owner_ref_from_env(environ=None):
    """The master pod's own identity (downward-API env POD_NAME /
    POD_UID, injected by the client's submit manifest) as an
    ownerReference dict — or None outside a cluster."""
    import os

    environ = os.environ if environ is None else environ
    name, uid = environ.get("POD_NAME"), environ.get("POD_UID")
    if not name or not uid:
        return None
    return {"name": name, "uid": uid}


def load_cluster_spec(path):
    """Import a cluster-spec module ('pkg.mod') exporting optional
    patch_pod / patch_service hooks."""
    if not path:
        return None
    return importlib.import_module(path)


def apply_spec_hook(spec_mod, manifest, hook_name):
    """Run a cluster-spec patch hook over a manifest dict (shared by
    the worker backend and the client submit path)."""
    hook = getattr(spec_mod, hook_name, None) if spec_mod else None
    if hook is not None:
        patched = hook(manifest)
        if patched is not None:
            return patched
    return manifest


def default_core_api():
    """Real kubernetes CoreV1Api with in-cluster-else-kubeconfig
    credentials (the one bootstrap both the backend and the client
    submit path share)."""
    try:
        from kubernetes import client, config
    except ImportError as e:
        raise ImportError(
            "this path needs the `kubernetes` package; install it in "
            "the cluster image (locally, use the process backend or "
            "--output manifest rendering)"
        ) from e
    try:
        config.load_incluster_config()
    except Exception:
        config.load_kube_config()
    return client.CoreV1Api()


class K8sWorkerBackend:
    def __init__(self, job_name, image, namespace="default",
                 worker_args=None, resources=None, tpu_topology=None,
                 num_workers=0, high_priority_fraction=0.0,
                 priority_class_high="high-priority",
                 priority_class_low="", cluster_spec="",
                 core_api=None, poll_secs=5.0, owner_ref=None,
                 volume=""):
        # None = build the real client lazily on first API call, so the
        # master can construct the backend (flag parsing, manifests)
        # before cluster credentials are needed.
        self._core_api = core_api
        self._job_name = job_name
        self._image = image
        self._namespace = namespace
        self._worker_args = worker_args or []
        self._resources = resources or {}
        self._tpu_topology = tpu_topology
        self._num_workers = num_workers
        self._high_fraction = high_priority_fraction
        self._priority_high = priority_class_high
        self._priority_low = priority_class_low
        self._cluster_spec = (
            load_cluster_spec(cluster_spec)
            if isinstance(cluster_spec, str) else cluster_spec
        )
        self._poll_secs = poll_secs
        # Master pod identity: stamped as ownerReference on every worker
        # pod/service, so deleting the master cascades the job (the
        # reference's ownership model, common/k8s_client.py:354-357).
        self._owner_ref = owner_ref
        from elasticdl_tpu.client.k8s_renderer import parse_volume_string

        # --volume mounts (reference k8s_volume.py semantics): applied
        # to every worker pod this backend launches.
        self._volumes, self._volume_mounts = parse_volume_string(volume)
        self._exit_events = {}  # pod name -> threading.Event w/ .code

    @property
    def _core(self):
        if self._core_api is None:
            self._core_api = default_core_api()
        return self._core_api

    @_core.setter
    def _core(self, api):
        self._core_api = api

    def _pod_name(self, worker_id):
        return "%s-worker-%d" % (self._job_name, worker_id)

    def _service_name(self, worker_id):
        return self._pod_name(worker_id)

    def _priority_class(self, slot):
        """First ceil(fraction*num_workers) *slots* ride the high
        priority class (reference pod_manager.py:80-99).  Keyed by slot,
        not launch id, so a relaunched high-priority worker keeps its
        protection instead of eroding the protected core."""
        if not self._high_fraction or not self._num_workers:
            return self._priority_low or None
        n_high = math.ceil(self._high_fraction * self._num_workers)
        if slot < n_high:
            return self._priority_high
        return self._priority_low or None

    def _attach_owner(self, manifest):
        if self._owner_ref:
            manifest["metadata"]["ownerReferences"] = [{
                "apiVersion": "v1",
                "kind": "Pod",
                "name": self._owner_ref["name"],
                "uid": self._owner_ref["uid"],
                "controller": True,
                "blockOwnerDeletion": True,
            }]
        return manifest

    def _apply_spec_hook(self, manifest, hook_name):
        return apply_spec_hook(self._cluster_spec, manifest, hook_name)

    def pod_manifest(self, worker_id, master_addr, slot=None,
                     extra_env=None):
        slot = worker_id if slot is None else slot
        manifest = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": self._pod_name(worker_id),
                "labels": {
                    LABEL_JOB: self._job_name,
                    LABEL_TYPE: "worker",
                    LABEL_INDEX: str(worker_id),
                },
            },
            "spec": {
                "restartPolicy": "Never",
                "containers": [{
                    "name": "worker",
                    "image": self._image,
                    "command": ["python", "-m",
                                "elasticdl_tpu.worker.main"],
                    "args": [str(a) for a in self._worker_args],
                    "env": [
                        {"name": "MASTER_ADDR", "value": master_addr},
                        {"name": "WORKER_ID", "value": str(worker_id)},
                    ] + [
                        {"name": k, "value": str(v)}
                        for k, v in sorted((extra_env or {}).items())
                    ],
                    "resources": {"requests": dict(self._resources)},
                }],
            },
        }
        if self._volumes:
            manifest["spec"]["volumes"] = [
                dict(v) for v in self._volumes
            ]
            manifest["spec"]["containers"][0]["volumeMounts"] = [
                dict(m) for m in self._volume_mounts
            ]
        if self._tpu_topology:
            manifest["spec"]["nodeSelector"] = {
                "cloud.google.com/gke-tpu-topology": self._tpu_topology
            }
        priority = self._priority_class(slot)
        if priority:
            manifest["spec"]["priorityClassName"] = priority
        self._attach_owner(manifest)
        return self._apply_spec_hook(manifest, "patch_pod")

    def service_manifest(self, worker_id, select_worker_id=None):
        """Service for a worker slot; ``select_worker_id`` lets a
        relaunch re-point the original slot's service at the
        replacement pod."""
        target = (
            worker_id if select_worker_id is None else select_worker_id
        )
        manifest = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": self._service_name(worker_id),
                "labels": {
                    LABEL_JOB: self._job_name,
                    LABEL_TYPE: "worker",
                    LABEL_INDEX: str(worker_id),
                },
            },
            "spec": {
                "selector": {
                    LABEL_JOB: self._job_name,
                    LABEL_TYPE: "worker",
                    LABEL_INDEX: str(target),
                },
                "ports": [{"port": 50002, "targetPort": 50002}],
            },
        }
        self._attach_owner(manifest)
        return self._apply_spec_hook(manifest, "patch_service")

    # -- WorkerManager backend surface --------------------------------------

    def slot_addresses(self, num_workers, port=50002):
        """Stable host:port per worker SLOT — the slot services point at
        whichever pod currently fills the slot, so these addresses stay
        valid across relaunches.  Feed them to
        ``cluster_spec_env.make_tf_config_fn`` for foreign-runtime
        cluster specs (reference pod_manager.py:405-422)."""
        return [
            "%s.%s.svc:%d" % (self._service_name(slot), self._namespace,
                              port)
            for slot in range(num_workers)
        ]

    def launch(self, worker_id, master_addr, slot=None, extra_env=None):
        """``slot`` is the stable replica slot (WorkerHandle.slot): on a
        relaunch it is the ORIGINAL slot id, so the slot's service keeps
        re-pointing at each replacement no matter how many times the
        worker dies."""
        slot = worker_id if slot is None else slot
        pod = self.pod_manifest(worker_id, master_addr, slot=slot,
                                extra_env=extra_env)
        self._core.create_namespaced_pod(self._namespace, pod)
        if slot != worker_id:
            # Keep the slot's service and re-point it at the replacement
            # (reference common/k8s_client.py:261-274).
            self.patch_service(slot, worker_id)
        else:
            self._core.create_namespaced_service(
                self._namespace, self.service_manifest(worker_id)
            )
        event = threading.Event()
        event.code = None
        self._exit_events[self._pod_name(worker_id)] = event
        return self._pod_name(worker_id)

    def patch_service(self, slot, new_worker_id):
        body = self.service_manifest(slot, select_worker_id=new_worker_id)
        try:
            self._core.patch_namespaced_service(
                self._service_name(slot), self._namespace, body
            )
        except Exception as e:  # noqa: BLE001 — service may be gone
            logger.warning(
                "patch service %s -> worker %d failed (%s); recreating",
                self._service_name(slot), new_worker_id, e,
            )
            try:
                # Self-heal: a missing/deleted slot service comes back
                # already selecting the replacement pod.
                self._core.create_namespaced_service(self._namespace, body)
            except Exception as e2:  # noqa: BLE001
                logger.warning(
                    "recreate service %s failed: %s",
                    self._service_name(slot), e2,
                )

    def wait(self, ref):
        """Block until the pod reaches a terminal phase; return an exit
        code (0 ok, 1 failed, 137 OOM, -9 deleted/preempted)."""
        event = self._exit_events[ref]
        while not event.wait(timeout=self._poll_secs):
            try:
                pod = self._core.read_namespaced_pod(ref, self._namespace)
            except Exception:
                event.code = -9  # pod gone: preempted/deleted
                break
            phase = self._phase(pod)
            if phase == "Succeeded":
                event.code = 0
                break
            if phase == "Failed":
                event.code = self._exit_code(pod)
                break
        self._exit_events.pop(ref, None)  # bound long-job growth
        return event.code

    @staticmethod
    def _phase(pod):
        if isinstance(pod, dict):
            return pod.get("status", {}).get("phase")
        return pod.status.phase

    @staticmethod
    def _exit_code(pod):
        code = 1
        if isinstance(pod, dict):
            statuses = pod.get("status", {}).get(
                "containerStatuses", []
            ) or []
            for s in statuses:
                term = (s.get("state") or {}).get("terminated")
                if term is not None:
                    code = term.get("exitCode", 1)
        else:
            for s in pod.status.container_statuses or []:
                term = s.state.terminated
                if term is not None:
                    code = term.exit_code
        return code

    def kill(self, ref, force=False):
        try:
            self._core.delete_namespaced_pod(
                ref, self._namespace,
                grace_period_seconds=0 if force else 30,
            )
        except Exception as e:
            logger.warning("delete pod %s failed: %s", ref, e)

    def is_alive(self, ref):
        try:
            pod = self._core.read_namespaced_pod(ref, self._namespace)
        except Exception:
            return False
        return self._phase(pod) in ("Pending", "Running")
