"""Kubernetes / TPU-VM worker backend.

Plugs into WorkerManager behind the same launch/wait/kill/is_alive surface
as ProcessWorkerBackend (parity with the reference's pod manager + k8s
client, elasticdl/python/master/pod_manager.py:207-674 and
common/k8s_client.py:41-334).  Requires the ``kubernetes`` package and
in-cluster (or kubeconfig) credentials; everything cluster-specific stays
in this one module so the rest of the control plane is backend-agnostic.

Pod labels follow the reference scheme: job name / replica-type /
replica-index.  Preemption shows up as pod DELETED events, which the
watcher maps to the same EV_PREEMPTED flow the process backend uses — so
TPU-VM preemption drills and local kill -9 drills exercise one code path.
"""

import threading

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

LABEL_JOB = "elasticdl-tpu-job-name"
LABEL_TYPE = "replica-type"
LABEL_INDEX = "replica-index"


class K8sWorkerBackend:
    def __init__(self, job_name, image, namespace="default",
                 worker_args=None, resources=None, tpu_topology=None):
        try:
            from kubernetes import client, config, watch  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "K8sWorkerBackend needs the `kubernetes` package; "
                "install it in the cluster image (the local image runs "
                "the process backend instead)"
            ) from e
        from kubernetes import client, config, watch

        try:
            config.load_incluster_config()
        except Exception:
            config.load_kube_config()
        self._core = client.CoreV1Api()
        self._watch = watch.Watch()
        self._job_name = job_name
        self._image = image
        self._namespace = namespace
        self._worker_args = worker_args or []
        self._resources = resources or {}
        self._tpu_topology = tpu_topology
        self._exit_events = {}  # pod name -> threading.Event w/ .code

    def _pod_name(self, worker_id):
        return "%s-worker-%d" % (self._job_name, worker_id)

    def _pod_manifest(self, worker_id, master_addr):
        from kubernetes import client

        env = [
            client.V1EnvVar(name="MASTER_ADDR", value=master_addr),
            client.V1EnvVar(name="WORKER_ID", value=str(worker_id)),
        ]
        node_selector = None
        if self._tpu_topology:
            node_selector = {
                "cloud.google.com/gke-tpu-topology": self._tpu_topology
            }
        return client.V1Pod(
            metadata=client.V1ObjectMeta(
                name=self._pod_name(worker_id),
                labels={
                    LABEL_JOB: self._job_name,
                    LABEL_TYPE: "worker",
                    LABEL_INDEX: str(worker_id),
                },
            ),
            spec=client.V1PodSpec(
                restart_policy="Never",
                node_selector=node_selector,
                containers=[
                    client.V1Container(
                        name="worker",
                        image=self._image,
                        command=["python", "-m",
                                 "elasticdl_tpu.worker.main"],
                        args=[str(a) for a in self._worker_args],
                        env=env,
                        resources=client.V1ResourceRequirements(
                            requests=self._resources
                        ),
                    )
                ],
            ),
        )

    # -- WorkerManager backend surface --------------------------------------

    def launch(self, worker_id, master_addr):
        pod = self._pod_manifest(worker_id, master_addr)
        self._core.create_namespaced_pod(self._namespace, pod)
        event = threading.Event()
        event.code = None
        self._exit_events[self._pod_name(worker_id)] = event
        return self._pod_name(worker_id)

    def wait(self, ref):
        """Block until the pod reaches a terminal phase; return an exit
        code (0 ok, 1 failed, -9 deleted/preempted)."""
        event = self._exit_events[ref]
        while not event.wait(timeout=5):
            try:
                pod = self._core.read_namespaced_pod(ref, self._namespace)
            except Exception:
                event.code = -9  # pod gone: preempted/deleted
                break
            phase = pod.status.phase
            if phase == "Succeeded":
                event.code = 0
                break
            if phase == "Failed":
                statuses = pod.status.container_statuses or []
                code = 1
                for s in statuses:
                    term = s.state.terminated
                    if term is not None:
                        code = term.exit_code
                event.code = 137 if code == 137 else code
                break
        return event.code

    def kill(self, ref, force=False):
        try:
            self._core.delete_namespaced_pod(
                ref, self._namespace,
                grace_period_seconds=0 if force else 30,
            )
        except Exception as e:
            logger.warning("delete pod %s failed: %s", ref, e)

    def is_alive(self, ref):
        try:
            pod = self._core.read_namespaced_pod(ref, self._namespace)
        except Exception:
            return False
        return pod.status.phase in ("Pending", "Running")
