"""Job-state journal — the master's crash-restart recovery log.

The master owns the only job state that (before this module) lived
purely in process memory: the task todo/doing queues, per-worker
progress counts, the model-version high-water mark, and the rendezvous
epoch.  Workers and PS shards already survive death (requeue /
relaunch-with-restore); this journal closes the last SPOF by making the
master recoverable too.

Design (docs/master_recovery.md):

 - **Append-only, crc-framed, durably flushed.**  One file,
   ``<journal_dir>/job.journal``; each record is ``<u32 length><u32
   crc32(payload)><payload>`` with a compact-JSON payload.  A torn
   write at the tail (power cut mid-fsync) is detected by the frame
   check and dropped LOUDLY on replay; the writer truncates the file
   back to the last valid frame before appending, so a restart never
   appends after garbage.

 - **Batched at the report cadence.**  Task *lifecycle* events
   (created/done/failed/epoch/rendezvous commits) are low-rate — one
   per task, not per batch — and each requests a group-commit flush
   (write + fdatasync on a dedicated flusher thread; N concurrent
   reporters share one sync and a handler never blocks on storage).
   High-rate *progress* events (per-window ``report_batch_done``
   counts, version reports) are buffered and ride the next lifecycle
   flush (or the size threshold), so the hot path pays a list append.
   A crash inside the flusher's ms-scale window downgrades the
   not-yet-durable tasks to the system's EXISTING at-least-once
   semantics (replay requeues them, exactly like the timeout
   watchdog); their progress events ride the same ordered buffer, so
   they vanish with their task and are never double-counted.
   Progress counts are observability, task accounting is the ground
   truth.

 - **Written OUTSIDE locks.**  ``JournalWriter.append``/``flush`` are
   file I/O and must never run inside a task-manager/servicer/
   rendezvous lock region (callers collect events under the lock and
   emit after release).  elastic-lint EL006 *proves* this: the journal
   methods are in the known-blocking registry
   (tools/elastic_lint/blocking.py), so a journal call under a lock is
   a lint failure, not a code-review hope.

Replay rebuilds a :class:`JournalState`; ``TaskManager.
restore_from_journal`` re-queues in-flight tasks, restores counts and
the epoch, and keeps the set of already-completed task ids so a worker
re-reporting a task it finished just before the crash is deduplicated
(idempotent success), never double-counted.
"""

import json
import os
import struct
import threading
import time
import zlib
from collections import defaultdict

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

JOURNAL_FILE = "job.journal"
_FRAME = struct.Struct("<II")

# Event types that drive task accounting: appending one via
# `journal_events` requests a group-commit flush (write + fdatasync on
# the flusher thread).  Everything else ("batch", "version",
# "dispatch", "requeue") is buffered progress riding the next flush.
DURABLE_EVENTS = frozenset(
    {"meta", "restart", "task", "done", "fail", "trim", "epoch", "cb",
     "rdzv", "sched"}
)

# Keep a bounded progress buffer: one fsync per this many buffered
# events even when no lifecycle event forces one.
DEFAULT_FLUSH_EVERY = 256


def journal_path(journal_dir):
    return os.path.join(journal_dir, JOURNAL_FILE)


def _encode(record):
    payload = json.dumps(
        record, separators=(",", ":"), sort_keys=True
    ).encode("utf-8")
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def scan_frames(data):
    """Yield (record, end_offset) for every valid frame in ``data``;
    stops LOUDLY at the first torn/corrupt frame (a crash mid-append
    legitimately leaves one) instead of crashing replay."""
    off, n = 0, len(data)
    while off < n:
        if off + _FRAME.size > n:
            logger.warning(
                "journal: truncated frame header at offset %d "
                "(%d trailing bytes dropped)", off, n - off,
            )
            return
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        if start + length > n:
            logger.warning(
                "journal: truncated record at offset %d (%d of %d "
                "payload bytes; tail dropped)", off, n - start, length,
            )
            return
        payload = data[start:start + length]
        if zlib.crc32(payload) != crc:
            logger.warning(
                "journal: crc mismatch at offset %d; dropping this "
                "and the remaining %d bytes", off, n - off,
            )
            return
        try:
            record = json.loads(payload)
        except ValueError:
            logger.warning(
                "journal: undecodable payload at offset %d; tail "
                "dropped", off,
            )
            return
        off = start + length
        yield record, off


class JournalWriter:
    """Thread-safe append-only writer.  ``append`` buffers; ``kick``
    requests a durable flush from the background flusher thread
    (group commit: one ``write`` + ``fdatasync`` covers every event
    buffered by then, so N concurrent reporters share one sync and an
    RPC handler never blocks on storage); ``flush`` is the synchronous
    drain for close/restart-marker/shutdown paths.  Opening an
    existing journal truncates any torn tail frame first (see module
    doc)."""

    def __init__(self, journal_dir, flush_every=DEFAULT_FLUSH_EVERY):
        os.makedirs(journal_dir, exist_ok=True)
        self._path = journal_path(journal_dir)
        # Two locks, strictly ordered _io_lock -> _lock: ``_lock``
        # guards ONLY the event buffer (what ``append`` needs, never
        # held across storage I/O), ``_io_lock`` serializes
        # write+fdatasync so concurrent flushes keep the buffer swaps
        # and the on-disk record order identical.
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._buf = []
        self._flush_every = max(1, int(flush_every))
        self._closed = False
        self._closing = False
        self._dirty = False
        valid = 0
        if os.path.exists(self._path):
            with open(self._path, "rb") as fh:
                data = fh.read()
            for _, end in scan_frames(data):
                valid = end
            if valid != len(data):
                logger.warning(
                    "journal: truncating %s from %d to last valid "
                    "frame at %d before appending",
                    self._path, len(data), valid,
                )
        self._fh = open(self._path, "ab")
        if valid != self._fh.tell():
            self._fh.truncate(valid)
            self._fh.seek(valid)
        self._flusher = threading.Thread(
            target=self._flush_loop, name="journal-flusher", daemon=True
        )
        self._flusher.start()

    def append(self, record):
        """Buffer one event; requests a flush at the size threshold."""
        # Encode outside the lock: the buffer lock is shared by every
        # RPC handler thread, and json+crc work doesn't need it.
        encoded = _encode(record)
        with self._lock:
            if self._closed:
                return
            self._buf.append(encoded)
            need_flush = len(self._buf) >= self._flush_every
        if need_flush:
            self.kick()

    def kick(self):
        """Request an asynchronous durable flush of everything
        buffered so far.  Returns immediately — the caller's events
        become durable within one flusher turnaround (ms), and a crash
        inside that window loses only events the system already
        tolerates losing: a not-yet-durable ``done`` replays as a
        requeue (the repo's existing at-least-once task semantics, the
        same as the timeout watchdog), and its progress events ride
        the SAME ordered buffer so they vanish with it, never
        double-counted."""
        # self._cv wraps self._lock, so holding the lock is holding
        # the condition's lock (and keeps EL001's guard map exact).
        with self._lock:
            self._dirty = True
            self._cv.notify()

    def _flush_loop(self):
        while True:
            with self._lock:
                while not (self._dirty or self._closing):
                    self._cv.wait()
                if not self._dirty:
                    return          # closing and drained: close() owns
                self._dirty = False
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 — the flusher must
                # outlive transient storage errors (ENOSPC, EIO, cgroup
                # throttle): flush() re-queued the events and re-armed
                # _dirty, so back off briefly and retry.  A dead
                # flusher would silently end durability while appends
                # accumulate unbounded.
                logger.error(
                    "journal: flush failed (%s); events re-queued, "
                    "retrying", e,
                )
                time.sleep(1.0)

    def flush(self):
        """Synchronous drain: write the buffer in one ``write`` and
        make it durable before returning.  The buffer is swapped out
        under ``_lock`` and the write+fdatasync runs under ``_io_lock``
        only, so a concurrent ``append`` (an RPC handler) NEVER waits
        on storage — a throttled fdatasync stalls the flusher, not the
        control plane."""
        with self._io_lock:
            with self._lock:
                if self._closed or not self._buf:
                    return
                blob = b"".join(self._buf)
                self._buf = []
            pos = self._fh.tell()
            try:
                self._fh.write(blob)
                self._fh.flush()
                # fdatasync, not fsync: the log is append-only, so the
                # only metadata a replay needs is the file size — which
                # fdatasync is required to make durable when it changed
                # (POSIX: "all I/O needed to retrieve the data").  ~40%
                # cheaper per durable event on this class of filesystem.
                os.fdatasync(self._fh.fileno())
            except Exception:
                # Self-heal: rewind any partial write (replay stops at
                # the first bad frame, so a torn frame MID-file would
                # poison everything after it) and put the events back
                # at the buffer front so a later flush retries
                # byte-identically.
                try:
                    self._fh.truncate(pos)
                    self._fh.seek(pos)
                except Exception:  # noqa: BLE001 — rewind best-effort
                    logger.error(
                        "journal: could not rewind after failed "
                        "flush; tail may be torn (replay tolerates)",
                    )
                with self._lock:
                    if not self._closed:
                        self._buf.insert(0, blob)
                        self._dirty = True
                raise

    def close(self):
        with self._lock:
            if self._closed or self._closing:
                return
            self._closing = True
            self._cv.notify_all()
        self._flusher.join(timeout=10)
        self.flush()
        with self._io_lock:
            with self._lock:
                self._closed = True
            self._fh.close()


def journal_events(journal, events):
    """Append a batch of events; request one group-commit flush if any
    is durable (the handler never blocks on storage — see
    ``JournalWriter.kick``).  No-op for ``journal=None`` so call sites
    stay unconditional.  MUST be called outside lock regions
    (EL006-enforced)."""
    if journal is None or not events:
        return
    durable = False
    for event in events:
        journal.append(event)
        durable = durable or event.get("ev") in DURABLE_EVENTS
    if durable:
        journal.kick()


class JournalState:
    """Replayed job state (see ``replay_journal``)."""

    def __init__(self):
        self.meta = None
        self.tasks = {}            # id -> task event dict
        self.status = {}           # id -> "todo" | "doing" | "done" | "failed"
        self.retries = {}          # id -> retry count at last fail
        self.completed_counts = defaultdict(int)   # task type -> n
        self.failed_counts = defaultdict(int)
        self.epoch = 0
        self.max_task_id = 0
        self.worker_records = defaultdict(int)     # worker id -> records
        self.records_done = 0
        self.model_version = 0
        self.rendezvous_id = 0
        self.restarts = 0
        self.train_end_pending = False
        self.train_end_created = False
        # Multi-tenant scheduler records (docs/scheduler.md): the
        # scheduler journal's "sched" events rebuild the worker->job
        # assignment map and the per-job admission state, so a master
        # killed MID-RESIZE replays to a consistent schedule (the
        # decision is journaled write-ahead of its effects).
        self.sched_assignments = {}     # worker id -> job id
        self.sched_jobs = {}            # job id -> {"name", "state"}
        self.sched_decisions = defaultdict(int)   # op -> count

    @property
    def done_ids(self):
        return {tid for tid, s in self.status.items() if s == "done"}

    def pending_tasks(self):
        """Tasks to rebuild the queue from: in-flight first (they were
        dispatched when the master died and must be requeued), then
        never-finished todo tasks, both in id order — the original
        creation order of the deque."""
        doing = sorted(
            tid for tid, s in self.status.items() if s == "doing"
        )
        todo = sorted(
            tid for tid, s in self.status.items() if s == "todo"
        )
        return [self.tasks[tid] for tid in doing + todo]

    def counts(self):
        return {
            "tasks": len(self.tasks),
            "done": sum(1 for s in self.status.values() if s == "done"),
            "doing": sum(1 for s in self.status.values() if s == "doing"),
            "todo": sum(1 for s in self.status.values() if s == "todo"),
            "failed": sum(
                1 for s in self.status.values() if s == "failed"
            ),
            "epoch": self.epoch,
            "records_done": self.records_done,
            "rendezvous_id": self.rendezvous_id,
            "restarts": self.restarts,
        }

    # -- event application --------------------------------------------------

    def apply(self, rec):
        ev = rec.get("ev")
        if ev == "meta":
            self.meta = rec.get("job", {})
        elif ev == "restart":
            self.restarts += 1
        elif ev == "task":
            tid = rec["id"]
            self.tasks[tid] = rec
            self.status[tid] = "todo"
            self.max_task_id = max(self.max_task_id, tid)
        elif ev == "dispatch":
            tid = rec["id"]
            if self.status.get(tid) == "todo":
                self.status[tid] = "doing"
        elif ev == "done":
            tid = rec["id"]
            if self.status.get(tid) not in (None, "done"):
                self.status[tid] = "done"
                self.completed_counts[self.tasks[tid]["type"]] += 1
        elif ev == "fail":
            tid = rec["id"]
            if self.status.get(tid) in ("todo", "doing"):
                self.retries[tid] = max(
                    self.retries.get(tid, 0), rec.get("retries", 0)
                )
                if rec.get("perm"):
                    self.status[tid] = "failed"
                    self.failed_counts[self.tasks[tid]["type"]] += 1
                else:
                    self.status[tid] = "todo"
        elif ev == "requeue":
            tid = rec["id"]
            if self.status.get(tid) == "doing":
                self.status[tid] = "todo"
        elif ev == "trim":
            task = self.tasks.get(rec["id"])
            if task is not None:
                trim = rec["start"] - task["start"]
                task["start"] = rec["start"]
                if task.get("idx") and trim > 0:
                    task["idx"] = task["idx"][trim:]
        elif ev == "epoch":
            self.epoch = max(self.epoch, rec["n"])
        elif ev == "cb":
            self.train_end_pending = True
        elif ev == "batch":
            self.worker_records[rec["w"]] += rec["n"]
            self.records_done += rec["n"]
        elif ev == "version":
            self.model_version = max(self.model_version, rec["v"])
        elif ev == "rdzv":
            self.rendezvous_id = max(self.rendezvous_id, rec["n"])
        elif ev == "sched":
            self._apply_sched(rec)
        else:
            logger.warning("journal: unknown event %r ignored", ev)

    def _apply_sched(self, rec):
        """One scheduler decision (record shapes in docs/scheduler.md):
        submit/admit/finish drive a job's admission state, assign moves
        a worker between jobs (``prev`` is its old job, 0 = fresh
        registration), release returns it to the unassigned pool.
        Later events win — replaying the whole journal yields exactly
        the assignment map the crashed master had made durable."""
        op = rec.get("op")
        if op in ("submit", "admit", "finish", "assign", "release"):
            # Count only ops this binary knows: a journal from a newer
            # master may carry future ops, and replayed counters must
            # match what this master would have counted live.
            self.sched_decisions[op] += 1
        if op == "submit":
            self.sched_jobs[rec["job"]] = {
                "name": rec.get("name", ""), "state": "pending",
            }
        elif op == "admit":
            self.sched_jobs.setdefault(
                rec["job"], {"name": "", "state": "pending"}
            )["state"] = "running"
        elif op == "finish":
            self.sched_jobs.setdefault(
                rec["job"], {"name": "", "state": "running"}
            )["state"] = "finished"
        elif op == "assign":
            self.sched_assignments[rec["w"]] = rec["job"]
        elif op == "release":
            self.sched_assignments.pop(rec["w"], None)
        else:
            logger.warning("journal: unknown sched op %r ignored", op)

    def finish(self):
        """Derived flags after the last event."""
        from elasticdl_tpu.proto import elastic_pb2 as pb

        self.train_end_created = any(
            t.get("type") == pb.TRAIN_END_CALLBACK
            for t in self.tasks.values()
        )
        return self


def replay_journal(journal_dir):
    """Rebuild the job state from the journal; None when the directory
    holds no journal (fresh start) or the journal has no records."""
    path = journal_path(journal_dir)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as fh:
        data = fh.read()
    state = JournalState()
    records = [record for record, _ in scan_frames(data)]
    n = len(records)
    # Two-pass apply: task CREATION records first, then everything
    # else in file order.  Handlers journal outside their locks, so a
    # stalled creator (say, the epoch-rollover get()) can append its
    # 'task' records AFTER another thread's 'dispatch'/'done' for
    # those very tasks reached the buffer; applying creations first
    # keeps such a completion from being silently dropped — and the
    # finished task from being re-run — on replay.  Lifecycle events
    # are order-tolerant given the task exists ('done' is absorbing,
    # 'dispatch' only applies from todo), and task ids are never
    # reused across restarts, so hoisting creations is safe.
    for record in records:
        if record.get("ev") == "task":
            state.apply(record)
    for record in records:
        if record.get("ev") != "task":
            state.apply(record)
    if n == 0:
        return None
    state.finish()
    logger.info(
        "journal: replayed %d records from %s: %s", n, path,
        state.counts(),
    )
    return state
