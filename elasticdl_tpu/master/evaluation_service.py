"""Version-triggered evaluation jobs.

Parity with elasticdl/python/master/evaluation_service.py:21-167: the PS (or
collective trainer) reports model versions; every ``evaluation_steps``
versions the master enqueues evaluation tasks at that version, workers run
forward passes and report (outputs, labels), and the master folds them into
streaming metrics.
"""

import threading

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class EvaluationJob:
    def __init__(self, metrics, model_version, total_tasks):
        self.model_version = model_version
        self._metrics = metrics
        self._total_tasks = total_tasks
        self._completed_tasks = 0

    def complete_task(self):
        self._completed_tasks += 1

    def finished(self):
        return self._completed_tasks >= self._total_tasks

    def report_evaluation_metrics(self, outputs, labels):
        for metric in self._metrics.values():
            metric.update(outputs, labels)

    def results(self):
        return {name: m.result() for name, m in self._metrics.items()}


class EvaluationService:
    def __init__(self, task_manager, metrics_factory, evaluation_steps=0):
        """metrics_factory() -> {name: Metric} builds fresh metrics per job."""
        self._task_manager = task_manager
        self._metrics_factory = metrics_factory
        self._evaluation_steps = evaluation_steps
        self._lock = threading.Lock()
        self._job = None
        self._creating = False
        self._creating_version = -1
        # Reports landing inside the creation window: the tasks become
        # dispatchable the moment create_evaluation_tasks releases the
        # task-manager lock (journal I/O runs outside OUR lock too), so
        # a fast worker can finish one before self._job is assigned.
        # Those completions/metrics are buffered and folded in when the
        # job lands — dropping them would leave the job permanently
        # unfinished and wedge every future evaluation.  Buffering is
        # version-gated: a straggler report from an already-finished
        # job (an RPC retry whose first attempt was processed) must
        # NOT leak into the job being created.
        self._pending_completions = 0
        self._pending_metrics = []
        self._last_eval_version = -1
        self.history = []  # [(model_version, {metric: value})]

    def add_evaluation_task_if_needed(self, model_version):
        if self._evaluation_steps <= 0:
            return False
        with self._lock:
            if self._creating:
                return False
            if (
                model_version // self._evaluation_steps
                <= self._last_eval_version // max(1, self._evaluation_steps)
                and self._last_eval_version >= 0
            ):
                return False
            if self._job is not None and not self._job.finished():
                return False
            # Reserve creation before releasing the lock: task creation
            # journals task records (file I/O that must not run under
            # this lock — EL006), and the reservation keeps a
            # concurrent version report from double-creating the job.
            self._creating = True
            self._creating_version = model_version
            self._pending_completions = 0
            self._pending_metrics = []
        try:
            total = self._task_manager.create_evaluation_tasks(
                model_version
            )
            with self._lock:
                if total == 0:
                    return False
                self._job = EvaluationJob(
                    self._metrics_factory(), model_version, total
                )
                self._last_eval_version = model_version
                for outputs, labels in self._pending_metrics:
                    self._job.report_evaluation_metrics(outputs, labels)
                self._pending_metrics = []
                for _ in range(self._pending_completions):
                    self._complete_one_locked()
                self._pending_completions = 0
            logger.info(
                "evaluation job created at version %d (%d tasks)",
                model_version, total,
            )
            return True
        finally:
            with self._lock:
                self._creating = False

    def report_evaluation_metrics(self, outputs, labels,
                                  model_version=-1):
        """``model_version`` tags the report with the job it belongs
        to (the eval task's version); -1 = unversioned, accepted
        against whatever job is live.  A versioned report that matches
        neither the live job nor the one being created is a straggler
        from a finished job and is dropped."""
        with self._lock:
            if self._job is None:
                if self._creating and self._version_matches_locked(
                    model_version, self._creating_version
                ):
                    self._pending_metrics.append((outputs, labels))
                    return True
                return False
            if not self._version_matches_locked(
                model_version, self._job.model_version
            ):
                return False
            self._job.report_evaluation_metrics(outputs, labels)
            return True

    def complete_task(self, model_version=-1):
        with self._lock:
            if self._job is None:
                if self._creating and self._version_matches_locked(
                    model_version, self._creating_version
                ):
                    self._pending_completions += 1
                return
            if self._version_matches_locked(
                model_version, self._job.model_version
            ):
                self._complete_one_locked()

    @staticmethod
    def _version_matches_locked(model_version, expected):
        return model_version < 0 or model_version == expected

    def _complete_one_locked(self):
        if self._job is None:
            return
        self._job.complete_task()
        if self._job.finished():
            results = self._job.results()
            self.history.append((self._job.model_version, results))
            logger.info(
                "evaluation @ version %d: %s",
                self._job.model_version,
                {k: round(v, 6) for k, v in results.items()},
            )
            # Retire the finished job immediately: if it stayed in
            # self._job, completions/metrics landing in the NEXT job's
            # creation window would be applied to it instead of the
            # pending buffers, leaving the new job one completion
            # short forever — the wedge the buffering exists to
            # prevent.
            self._job = None
