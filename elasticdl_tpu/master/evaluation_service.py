"""Version-triggered evaluation jobs.

Parity with elasticdl/python/master/evaluation_service.py:21-167: the PS (or
collective trainer) reports model versions; every ``evaluation_steps``
versions the master enqueues evaluation tasks at that version, workers run
forward passes and report (outputs, labels), and the master folds them into
streaming metrics.
"""

import threading

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class EvaluationJob:
    def __init__(self, metrics, model_version, total_tasks):
        self.model_version = model_version
        self._metrics = metrics
        self._total_tasks = total_tasks
        self._completed_tasks = 0

    def complete_task(self):
        self._completed_tasks += 1

    def finished(self):
        return self._completed_tasks >= self._total_tasks

    def report_evaluation_metrics(self, outputs, labels):
        for metric in self._metrics.values():
            metric.update(outputs, labels)

    def results(self):
        return {name: m.result() for name, m in self._metrics.items()}


class EvaluationService:
    def __init__(self, task_manager, metrics_factory, evaluation_steps=0):
        """metrics_factory() -> {name: Metric} builds fresh metrics per job."""
        self._task_manager = task_manager
        self._metrics_factory = metrics_factory
        self._evaluation_steps = evaluation_steps
        self._lock = threading.Lock()
        self._job = None
        self._last_eval_version = -1
        self.history = []  # [(model_version, {metric: value})]

    def add_evaluation_task_if_needed(self, model_version):
        if self._evaluation_steps <= 0:
            return False
        with self._lock:
            if (
                model_version // self._evaluation_steps
                <= self._last_eval_version // max(1, self._evaluation_steps)
                and self._last_eval_version >= 0
            ):
                return False
            if self._job is not None and not self._job.finished():
                return False
            total = self._task_manager.create_evaluation_tasks(model_version)
            if total == 0:
                return False
            self._job = EvaluationJob(
                self._metrics_factory(), model_version, total
            )
            self._last_eval_version = model_version
            logger.info(
                "evaluation job created at version %d (%d tasks)",
                model_version, total,
            )
            return True

    def report_evaluation_metrics(self, outputs, labels):
        with self._lock:
            if self._job is None:
                return False
            self._job.report_evaluation_metrics(outputs, labels)
            return True

    def complete_task(self):
        with self._lock:
            if self._job is None:
                return
            self._job.complete_task()
            if self._job.finished():
                results = self._job.results()
                self.history.append((self._job.model_version, results))
                logger.info(
                    "evaluation @ version %d: %s",
                    self._job.model_version,
                    {k: round(v, 6) for k, v in results.items()},
                )
