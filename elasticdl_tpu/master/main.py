"""Master entrypoint (parity: elasticdl/python/master/main.py:20-24).

Builds the control plane from flags, optionally launches/manages workers
(local-process backend), runs the job to completion.
"""

import os

from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.master.worker_manager import (
    ProcessWorkerBackend,
    WorkerManager,
)
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.args import (
    build_arguments_from_parsed_result,
    parse_master_args,
)
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_MASTER_ONLY_ARGS = (
    "port", "num_workers", "num_ps", "shuffle", "shuffle_shards",
    "max_task_retries", "task_timeout_secs", "relaunch_on_worker_failure",
    "grads_to_wait", "sync_version_tolerance",
    "worker_backend", "image", "namespace", "worker_resource_request",
    "tpu_topology", "worker_pod_priority", "cluster_spec", "volume",
    "status_port", "journal_dir", "rpc_fault_spec",
    "ps_rpc_fault_spec",
    "jobs_spec", "sched_cadence_secs", "sched_moves_per_tick",
    "sched_worker_stale_secs",
)

# Job-config fields that must match between the journal and a
# restarted master's flags: replaying a journal into a DIFFERENT job
# (other dataset, other task split) would rebuild nonsense queues.
_JOURNAL_META_FIELDS = (
    "job_name", "job_type", "data_origin", "records_per_task",
    "num_epochs", "seed", "shuffle", "shuffle_shards",
)


def _journal_meta(args, records_per_task):
    meta = {
        field: getattr(args, field) for field in _JOURNAL_META_FIELDS
        if field != "records_per_task"
    }
    meta["records_per_task"] = records_per_task
    return meta


def _check_journal_meta(state, meta):
    if state.meta is None:
        logger.warning("journal has no meta record; replaying anyway")
        return
    mismatched = {
        k: (state.meta.get(k), meta[k])
        for k in meta if state.meta.get(k) != meta[k]
    }
    if mismatched:
        raise RuntimeError(
            "journal replay refused: the journaled job does not match "
            "this master's flags (journaled vs current): %r — point "
            "--journal_dir at a fresh directory for a new job"
            % mismatched
        )


def _build_worker_backend(args, worker_args):
    if args.worker_backend == "k8s":
        from elasticdl_tpu.client.k8s_renderer import parse_resource_string
        from elasticdl_tpu.master.k8s_backend import (
            K8sWorkerBackend,
            owner_ref_from_env,
        )

        return K8sWorkerBackend(
            job_name=args.job_name,
            image=args.image,
            namespace=args.namespace,
            worker_args=worker_args,
            resources=parse_resource_string(args.worker_resource_request),
            tpu_topology=args.tpu_topology or None,
            num_workers=args.num_workers,
            high_priority_fraction=args.worker_pod_priority,
            cluster_spec=args.cluster_spec,
            owner_ref=owner_ref_from_env(),
            volume=args.volume,
        )
    return ProcessWorkerBackend(worker_args=worker_args)


def build_master(args):
    records_per_task = args.batch_size * args.num_minibatches_per_task
    journal_state = None
    if args.journal_dir:
        from elasticdl_tpu.master.journal import replay_journal

        # The recovery trace: journal replay is this incarnation's
        # root recovery span; every later event this master records
        # carries link_trace back to it, so a worker's outage-riding
        # trace and the replay stitch into ONE incident component
        # (docs/observability.md, cpu_master_kill drill gate).
        with tracing.span("master.journal_replay") as replay_span:
            journal_state = replay_journal(args.journal_dir)
            if journal_state is not None:
                tracing.event(
                    "journal.replayed",
                    restarts=journal_state.restarts,
                    rendezvous_id=journal_state.rendezvous_id,
                )
        if journal_state is not None:
            restart = journal_state.restarts + 1
            tracing.configure_identity(
                "master", generation=restart, restart=restart,
                # replay_span is None when tracing is disabled
                link_trace=getattr(replay_span, "trace", None),
            )
    reader = create_data_reader(
        args.data_origin, records_per_shard=records_per_task
    )
    eval_reader = None
    if args.validation_data_origin:
        eval_reader = create_data_reader(
            args.validation_data_origin, records_per_shard=records_per_task
        )
    common = dict(
        records_per_task=records_per_task,
        num_epochs=args.num_epochs,
        shuffle=args.shuffle,
        shuffle_shards=args.shuffle_shards,
        max_task_retries=args.max_task_retries,
        task_timeout_secs=args.task_timeout_secs,
        seed=args.seed,
    )
    if args.job_type == "predict":
        task_manager = TaskManager(
            prediction_shards=reader.create_shards(), **common
        )
    elif args.job_type == "evaluate":
        task_manager = TaskManager(
            evaluation_shards=reader.create_shards(), **common
        )
    else:
        task_manager = TaskManager(
            training_shards=reader.create_shards(),
            evaluation_shards=(
                eval_reader.create_shards() if eval_reader else None
            ),
            **common,
        )
    journal = None
    if args.journal_dir:
        from elasticdl_tpu.master.journal import JournalWriter

        journal = JournalWriter(args.journal_dir)
    if journal_state is not None:
        # Master crash-restart: the journal is the exact task/progress
        # state — replaying it supersedes the checkpoint-version
        # skip_records approximation below.
        _check_journal_meta(
            journal_state, _journal_meta(args, records_per_task)
        )
        task_manager.restore_from_journal(journal_state)
        journal.append({"ev": "restart"})
        journal.flush()
        task_manager.attach_journal(journal, bootstrap=False)
    else:
        if journal is not None:
            journal.append(
                {"ev": "meta",
                 "job": _journal_meta(args, records_per_task)}
            )
            # Attach BEFORE any checkpoint skip below, so the skip's
            # done/trim events land in the journal too.
            task_manager.attach_journal(journal, bootstrap=True)
        if args.job_type == "train" and args.checkpoint_dir:
            # Resume: the checkpoint version counts optimizer steps;
            # skip the records those steps consumed so epoch 1
            # continues where the previous run stopped.
            from elasticdl_tpu.utils.checkpoint import CheckpointSaver

            latest = CheckpointSaver(
                args.checkpoint_dir
            ).latest_resumable_version(max(args.num_ps, 1))
            if latest:
                task_manager.skip_records(latest * args.batch_size)
    spec = load_model_spec(args.model_zoo,
                           model_params=args.model_params)
    evaluation_service = None
    if args.job_type == "evaluate":
        if spec.eval_metrics_fn is None:
            raise ValueError(
                "evaluate job requires eval_metrics_fn in the model spec"
            )
        evaluation_service = EvaluationService(
            task_manager, spec.eval_metrics_fn, evaluation_steps=1
        )
        evaluation_service.add_evaluation_task_if_needed(0)
    elif (
        args.evaluation_steps
        and eval_reader is not None
        and spec.eval_metrics_fn is not None
    ):
        evaluation_service = EvaluationService(
            task_manager,
            spec.eval_metrics_fn,
            evaluation_steps=args.evaluation_steps,
        )
    if spec.callbacks:
        # One worker runs on_train_end (model export) after the last
        # training task (reference: deferred train-end task,
        # task_manager.py:35-68 + callbacks.py:23-66).
        task_manager.set_train_end_callback_task()
    rendezvous = None
    if args.distribution_strategy == "collective":
        from elasticdl_tpu.parallel.distributed import (
            MasterCoordinationService,
            derive_reap_secs,
        )

        # The master hosts the per-epoch JAX coordination service so
        # worker churn can never strand the survivors (see
        # docs/designs/elastic_collectives.md).  Per-epoch services
        # bind fresh ports the master's k8s Service does NOT map, so
        # workers must dial the master POD itself: POD_IP (downward
        # API, injected by the submission manifest) on k8s, localhost
        # for process workers.  Fail fast when it's missing — a
        # Service-DNS fallback would only produce opaque worker-side
        # connect timeouts.
        if args.worker_backend == "k8s":
            coord_host = os.environ.get("POD_IP")
            if not coord_host:
                raise RuntimeError(
                    "collective strategy on k8s requires the POD_IP "
                    "downward-API env (the per-epoch coordination "
                    "ports are not mapped by the master Service); "
                    "resubmit with a current client — "
                    "client/k8s_submit.py injects it"
                )
        else:
            coord_host = "localhost"
        rendezvous = RendezvousServer(
            coordinator_factory=MasterCoordinationService(
                host=coord_host,
                # Old-epoch services must outlive the workers'
                # worst-case epoch discovery: workers poll every
                # num_minibatches_per_task steps (worker/main.py
                # passes the same value as check_steps).
                reap_secs=derive_reap_secs(
                    check_steps=max(1, args.num_minibatches_per_task)
                ),
            ).start_epoch,
            journal=journal,
            # Restart re-arms STRICTLY past every epoch a worker can
            # hold (journaled id, +1 for an un-journaled commit racing
            # the crash) so reconnecting workers re-form at a fresh id.
            initial_epoch=(
                journal_state.rendezvous_id + 1 if journal_state else 0
            ),
        )
    ps_manager = None
    if args.distribution_strategy == "ps" and args.num_ps > 0:
        from elasticdl_tpu.master.ps_manager import PSManager

        opt_type, opt_args = spec.ps_optimizer
        ps_manager = PSManager(
            args.num_ps, opt_type, opt_args,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_steps=args.checkpoint_steps,
            evaluation_steps=args.evaluation_steps,
            use_async=args.use_async,
            grads_to_wait=args.grads_to_wait,
            sync_version_tolerance=args.sync_version_tolerance,
            # Worker->PS drills: each shard arms this as its own
            # --rpc_fault_spec (docs/ps_recovery.md).
            ps_fault_spec=args.ps_rpc_fault_spec,
        )
    worker_manager = None
    if args.num_workers > 0:
        worker_args = build_arguments_from_parsed_result(
            args, filter_args=_MASTER_ONLY_ARGS
        )
        if ps_manager is not None:
            worker_args += ["--ps_addrs", ps_manager.addrs]
        worker_manager = WorkerManager(
            _build_worker_backend(args, worker_args),
            num_workers=args.num_workers,
            max_relaunch_count=args.relaunch_on_worker_failure,
        )
    port = args.port
    if args.worker_backend == "k8s" and not port:
        # Pods dial the master through its Service, whose targetPort is
        # fixed (client/k8s_submit.py MASTER_PORT) — a free-port bind
        # would be unreachable.
        from elasticdl_tpu.client.k8s_submit import MASTER_PORT

        port = MASTER_PORT
    interceptors = None
    if args.rpc_fault_spec:
        from elasticdl_tpu.utils.grpc_utils import (
            FaultInjectionInterceptor,
        )

        logger.warning(
            "RPC fault injection armed: %s", args.rpc_fault_spec
        )
        interceptors = [FaultInjectionInterceptor(args.rpc_fault_spec)]
    master = Master(
        task_manager,
        rendezvous_server=rendezvous,
        evaluation_service=evaluation_service,
        worker_manager=worker_manager,
        port=port,
        journal=journal,
        interceptors=interceptors,
    )
    if journal_state is not None:
        master.servicer.restore_from_journal(journal_state)
    if args.worker_backend == "k8s":
        # Workers in other pods reach the master by its service DNS
        # name, not localhost (the service the submit path created).
        master.advertise_addr = "%s-master.%s.svc:%%d" % (
            args.job_name, args.namespace
        )
    master.ps_manager = ps_manager
    return master


def _load_jobs_spec(text):
    """--jobs_spec accepts inline JSON or a path to a JSON file; the
    value is a list of job-spec dicts (docs/scheduler.md)."""
    import json

    if os.path.exists(text):
        with open(text) as fh:
            text = fh.read()
    spec = json.loads(text)
    if not isinstance(spec, list) or not spec:
        raise ValueError(
            "--jobs_spec must be a non-empty JSON list of job specs"
        )
    return spec


def build_multitenant_master(args):
    """The multi-tenant control plane (master/scheduler.py): J jobs,
    each with its own task queue, rendezvous epoch space, journal
    namespace and telemetry aggregate, over ONE shared worker pool
    driven by the resize controller.  Train-type local/collective jobs
    only — a PS-mode job keeps its own single-job master."""
    from elasticdl_tpu.master.journal import (
        JournalWriter,
        replay_journal,
    )
    from elasticdl_tpu.master.scheduler import (
        JobRegistry,
        JobSpec,
        ManagedJob,
        MultiTenantMaster,
        ResizeController,
    )
    from elasticdl_tpu.master.servicer import MasterServicer

    specs = [
        JobSpec.from_dict(entry, defaults=args)
        for entry in _load_jobs_spec(args.jobs_spec)
    ]
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError("duplicate job names in --jobs_spec: %s"
                         % names)
    if args.num_workers > 0:
        # A managed pool never grows past --num_workers, so a job
        # whose floor exceeds it could NEVER be admitted — the run
        # would hang forever in the admission queue.  Fail fast.
        impossible = [
            s.name for s in specs if s.min_workers > args.num_workers
        ]
        if impossible:
            raise ValueError(
                "jobs %s require min_workers > --num_workers (%d) "
                "and could never be admitted" % (impossible,
                                                 args.num_workers)
            )
    sched_journal = None
    sched_state = None
    if args.journal_dir:
        sched_dir = os.path.join(args.journal_dir, "sched")
        # The recovery trace (same contract as the single-job path):
        # replaying the scheduler journal is this incarnation's root
        # recovery span; post-replay events link back to it so worker
        # outage rides and the restarted schedule stitch into one
        # incident component (the cpu_multitenant drill gate).
        with tracing.span("master.journal_replay") as replay_span:
            sched_state = replay_journal(sched_dir)
        if sched_state is not None:
            restart = sched_state.restarts + 1
            tracing.configure_identity(
                "master", generation=restart, restart=restart,
                link_trace=getattr(replay_span, "trace", None),
            )
        sched_journal = JournalWriter(sched_dir)
        sched_meta = {"jobs": names, "multitenant": True}
        if sched_state is not None:
            _check_journal_meta(sched_state, sched_meta)
            sched_journal.append({"ev": "restart"})
            sched_journal.flush()
        else:
            sched_journal.append({"ev": "meta", "job": sched_meta})
    registry = JobRegistry(
        journal=sched_journal, pool_size=args.num_workers
    )
    for index, spec in enumerate(specs):
        job_id = index + 1   # deterministic: spec order, 1-based (0 =
        #                      "unscoped" on the wire)
        records_per_task = spec.records_per_task
        reader = create_data_reader(
            spec.data_origin, records_per_shard=records_per_task
        )
        task_manager = TaskManager(
            training_shards=reader.create_shards(),
            records_per_task=records_per_task,
            num_epochs=spec.num_epochs,
            shuffle=spec.shuffle,
            shuffle_shards=spec.shuffle_shards,
            max_task_retries=args.max_task_retries,
            task_timeout_secs=args.task_timeout_secs,
            seed=spec.seed,
        )
        job_journal = None
        job_state = None
        if args.journal_dir:
            job_dir = os.path.join(args.journal_dir,
                                   "job-%02d" % job_id)
            job_state = replay_journal(job_dir)
            job_journal = JournalWriter(job_dir)
            if job_state is not None:
                _check_journal_meta(job_state, spec.journal_meta())
                task_manager.restore_from_journal(job_state)
                job_journal.append({"ev": "restart"})
                job_journal.flush()
                task_manager.attach_journal(job_journal,
                                            bootstrap=False)
            else:
                job_journal.append(
                    {"ev": "meta", "job": spec.journal_meta()}
                )
                task_manager.attach_journal(job_journal,
                                            bootstrap=True)
        rendezvous = None
        if spec.distribution_strategy == "collective":
            # Per-job epoch space.  No coordinator factory: pool
            # workers keep process-local device meshes (the same
            # regime the elastic drills run); every join/leave still
            # commits a real journaled epoch for this job only.
            rendezvous = RendezvousServer(
                journal=job_journal,
                initial_epoch=(
                    job_state.rendezvous_id + 1 if job_state else 0
                ),
                name=spec.name,
            )
        servicer = MasterServicer(
            task_manager, rendezvous_server=rendezvous,
            journal=job_journal, job_id=job_id,
        )
        if job_state is not None:
            servicer.restore_from_journal(job_state)
        job = ManagedJob(
            job_id, spec, task_manager, servicer,
            rendezvous=rendezvous, journal=job_journal,
        )
        registry.submit(job, journal=sched_state is None)
    if sched_state is not None:
        registry.restore_from_journal(sched_state)
    worker_manager = None
    if args.num_workers > 0:
        worker_args = build_arguments_from_parsed_result(
            args, filter_args=_MASTER_ONLY_ARGS
        )
        worker_manager = WorkerManager(
            _build_worker_backend(args, worker_args),
            num_workers=args.num_workers,
            max_relaunch_count=args.relaunch_on_worker_failure,
        )
    controller = ResizeController(
        registry, worker_manager=worker_manager,
        cadence_secs=args.sched_cadence_secs,
        moves_per_tick=args.sched_moves_per_tick,
        worker_stale_secs=args.sched_worker_stale_secs,
    )
    interceptors = None
    if args.rpc_fault_spec:
        from elasticdl_tpu.utils.grpc_utils import (
            FaultInjectionInterceptor,
        )

        logger.warning(
            "RPC fault injection armed: %s", args.rpc_fault_spec
        )
        interceptors = [FaultInjectionInterceptor(args.rpc_fault_spec)]
    return MultiTenantMaster(
        registry, controller, worker_manager=worker_manager,
        port=args.port, sched_journal=sched_journal,
        interceptors=interceptors,
    )


def _arm_master_slo(servicers):
    """Default master SLO: zero sustained stragglers (the acceptance
    objective the straggler detector feeds — a flagged worker IS a
    breach on /alertz and an ``slo.breach`` flight-recorder event),
    plus any operator rules from $ELASTICDL_SLO_SPEC."""
    from elasticdl_tpu.utils import slo as slo_mod

    wd = slo_mod.default_watchdog()
    wd.add_source(
        "straggler_workers",
        lambda: float(sum(len(s.stragglers()) for s in servicers())))
    wd.add_rule("value(straggler_workers) < 1", name="stragglers",
                description="no worker sustained-flagged as a "
                            "straggler (cross-worker step-time skew)")
    wd.arm_from_env()


def _run_multitenant(args):
    master = build_multitenant_master(args)
    master.prepare()
    _arm_master_slo(
        lambda: [job.servicer for job in master.registry.jobs()])
    status_server = None
    if args.status_port >= 0:
        from elasticdl_tpu.master.status_server import (
            MultiTenantStatusServer,
        )

        status_server = MultiTenantStatusServer(
            master.registry, worker_manager=master.worker_manager,
            port=args.status_port,
        )
        status_server.start()
    try:
        return master.run()
    finally:
        if status_server is not None:
            status_server.stop()
        for job in master.registry.jobs():
            if job.journal is not None:
                job.journal.close()
        if master.sched_journal is not None:
            master.sched_journal.close()


def main(argv=None):
    args = parse_master_args(argv)
    tracing.configure_identity("master")
    tracing.arm_crash_dump()
    logger.info("master starting: %s", vars(args))
    if args.jobs_spec:
        return _run_multitenant(args)
    master = build_master(args)
    master.prepare()
    _arm_master_slo(lambda: [master.servicer])
    status_server = None
    if args.status_port >= 0:
        from elasticdl_tpu.master.status_server import StatusServer

        status_server = StatusServer(
            master.task_manager,
            worker_manager=master.worker_manager,
            rendezvous_server=master.rendezvous_server,
            servicer=master.servicer,
            port=args.status_port,
        )
        status_server.start()
    if getattr(master, "ps_manager", None) is not None:
        master.ps_manager._master_addr = "localhost:%d" % master.port
        master.ps_manager.start()
    try:
        return master.run()
    finally:
        if getattr(master, "ps_manager", None) is not None:
            master.ps_manager.stop()
        if status_server is not None:
            status_server.stop()
        if master.journal is not None:
            master.journal.close()


if __name__ == "__main__":
    raise SystemExit(main())
