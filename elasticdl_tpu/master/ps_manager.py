"""Parameter-server process lifecycle.

The master launches/watches/relaunches PS shards the way it does workers
(reference: PS pods in pod_manager, protected by priority; relaunch uses
``checkpoint_dir_for_init`` so a fresh shard restores its hash-routed slice
of the newest COMMITTED cross-shard checkpoint — go/pkg/ps/checkpoint.go
semantics, barrier semantics in docs/ps_recovery.md).  Each launch passes
a ``--generation`` hint (this manager's per-shard launch count) so a
relaunched shard serves as a strictly newer restart generation even when
its checkpoint dir — where the generation normally persists — was lost
with the pod.

The relaunch budget DECAYS: a shard that stayed healthy for
``relaunch_decay_secs`` before dying gets its count reset, so a long job
surviving occasional preemptions never exhausts ``max_relaunch`` forever
— the budget bounds crash *loops*, not total preemptions.  ``stop()``
escalates terminate→kill with a bounded wait so a wedged shard cannot
hang teardown.
"""

import os
import subprocess
import sys
import threading
import time

from elasticdl_tpu.utils.grpc_utils import find_free_port
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class PSManager:
    # A shard that survives this long is considered to have exited its
    # crash loop: the next death starts a fresh relaunch budget.
    DEFAULT_RELAUNCH_DECAY_SECS = 300.0
    # stop(): grace between SIGTERM and SIGKILL, and the bounded wait
    # after SIGKILL (a kill can only be outwaited by a kernel wedge).
    STOP_GRACE_SECS = 5.0
    STOP_KILL_WAIT_SECS = 5.0

    def __init__(self, num_ps, opt_type, opt_args, master_addr="",
                 checkpoint_dir="", checkpoint_steps=0,
                 evaluation_steps=0, use_async=True, grads_to_wait=1,
                 sync_version_tolerance=0, max_relaunch=5,
                 relaunch_decay_secs=None, ps_fault_spec=""):
        self.num_ps = num_ps
        self._opt_type = opt_type
        self._opt_args = opt_args
        self._master_addr = master_addr
        self._checkpoint_dir = checkpoint_dir
        self._checkpoint_steps = checkpoint_steps
        self._evaluation_steps = evaluation_steps
        self._use_async = use_async
        self._grads_to_wait = grads_to_wait
        self._sync_version_tolerance = sync_version_tolerance
        self._max_relaunch = max_relaunch
        self._relaunch_decay_secs = (
            self.DEFAULT_RELAUNCH_DECAY_SECS
            if relaunch_decay_secs is None else float(relaunch_decay_secs)
        )
        # Deterministic worker->PS fault drills: forwarded to every
        # shard as its --rpc_fault_spec (docs/master_recovery.md
        # grammar; the cpu_ps_kill drill leans on this).
        self._ps_fault_spec = ps_fault_spec
        self.ports = [find_free_port() for _ in range(num_ps)]
        self._procs = {}
        self._relaunches = {}
        self._launch_counts = {}   # ps_id -> total launches (gen hint)
        self._launched_at = {}     # ps_id -> monotonic launch time
        self._stopped = threading.Event()
        self._lock = threading.Lock()

    @property
    def addrs(self):
        return ",".join("localhost:%d" % p for p in self.ports)

    def _args(self, ps_id, restore, generation):
        args = [
            "--port", str(self.ports[ps_id]),
            "--ps_id", str(ps_id),
            "--num_ps", str(self.num_ps),
            "--opt_type", self._opt_type,
            "--opt_args", self._opt_args,
            "--use_async", str(self._use_async),
            "--grads_to_wait", str(self._grads_to_wait),
            "--sync_version_tolerance", str(self._sync_version_tolerance),
            "--evaluation_steps", str(self._evaluation_steps),
            # Restart-generation hint: the shard serves as
            # max(persisted+1, hint) so relaunches fence even when the
            # persisted counter vanished with the pod's disk.
            "--generation", str(generation),
        ]
        if self._master_addr:
            args += ["--master_addr", self._master_addr]
        if self._ps_fault_spec:
            args += ["--rpc_fault_spec", self._ps_fault_spec]
        if self._checkpoint_dir:
            args += [
                "--checkpoint_dir", self._checkpoint_dir,
                "--checkpoint_steps", str(self._checkpoint_steps),
            ]
            if restore:
                args += ["--checkpoint_dir_for_init",
                         self._checkpoint_dir]
        return args

    def _launch(self, ps_id, restore=False):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        with self._lock:
            if self._stopped.is_set():
                return
            count = self._launch_counts.get(ps_id, 0) + 1
            self._launch_counts[ps_id] = count
            proc = subprocess.Popen(
                [sys.executable, "-m", "elasticdl_tpu.ps.server"]
                + self._args(ps_id, restore, count),
                env=env,
            )
            self._procs[ps_id] = proc
            self._launched_at[ps_id] = time.monotonic()
        logger.info("launched PS %d on port %d (restore=%s, "
                    "generation hint %d)",
                    ps_id, self.ports[ps_id], restore, count)
        threading.Thread(
            target=self._watch, args=(ps_id, proc),
            name="ps-watch-%d" % ps_id, daemon=True,
        ).start()

    def _watch(self, ps_id, proc):
        code = proc.wait()
        if self._stopped.is_set():
            return
        with self._lock:
            launched = self._launched_at.get(ps_id, 0.0)
        uptime = time.monotonic() - launched
        count = self._relaunches.get(ps_id, 0)
        if count and uptime >= self._relaunch_decay_secs:
            # The shard rode out its previous trouble and served
            # healthily for a sustained window: this death opens a
            # fresh budget instead of inching toward permanent death
            # on a long job's occasional preemptions.
            logger.info(
                "PS %d was healthy %.0fs (>= %.0fs): relaunch budget "
                "reset (%d -> 0)", ps_id, uptime,
                self._relaunch_decay_secs, count,
            )
            count = 0
        if count >= self._max_relaunch:
            logger.error("PS %d died (code %s); relaunch budget spent",
                         ps_id, code)
            return
        self._relaunches[ps_id] = count + 1
        logger.warning("PS %d died (code %s); relaunching with restore",
                       ps_id, code)
        self._launch(ps_id, restore=bool(self._checkpoint_dir))

    def start(self):
        for ps_id in range(self.num_ps):
            self._launch(ps_id)

    def stop(self):
        # Flag first under the lock so no in-flight _watch relaunch can
        # spawn an orphan after we start terminating.
        with self._lock:
            self._stopped.set()
            procs = list(self._procs.values())
        live = [p for p in procs if p.poll() is None]
        for proc in live:
            proc.terminate()
        # Bounded escalation: give the fleet one shared grace window,
        # then SIGKILL stragglers — a shard wedged mid-checkpoint (or
        # with a stuck gRPC thread) must not hang job teardown.
        deadline = time.monotonic() + self.STOP_GRACE_SECS
        for proc in live:
            try:
                proc.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                logger.warning(
                    "PS pid %d ignored SIGTERM for %.0fs; killing",
                    proc.pid, self.STOP_GRACE_SECS,
                )
                proc.kill()
        deadline = time.monotonic() + self.STOP_KILL_WAIT_SECS
        for proc in live:
            if proc.poll() is None:
                try:
                    proc.wait(
                        timeout=max(0.0, deadline - time.monotonic())
                    )
                except subprocess.TimeoutExpired:
                    logger.error("PS pid %d survived SIGKILL wait; "
                                 "abandoning reap", proc.pid)
