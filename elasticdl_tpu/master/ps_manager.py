"""Parameter-server process lifecycle.

The master launches/watches/relaunches PS shards the way it does workers
(reference: PS pods in pod_manager, protected by priority; relaunch uses
``checkpoint_dir_for_init`` so a fresh shard restores its hash-routed slice
of the latest checkpoint — go/pkg/ps/checkpoint.go:98-133 semantics).
"""

import os
import subprocess
import sys
import threading

from elasticdl_tpu.utils.grpc_utils import find_free_port
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class PSManager:
    def __init__(self, num_ps, opt_type, opt_args, master_addr="",
                 checkpoint_dir="", checkpoint_steps=0,
                 evaluation_steps=0, use_async=True, grads_to_wait=1,
                 sync_version_tolerance=0, max_relaunch=5):
        self.num_ps = num_ps
        self._opt_type = opt_type
        self._opt_args = opt_args
        self._master_addr = master_addr
        self._checkpoint_dir = checkpoint_dir
        self._checkpoint_steps = checkpoint_steps
        self._evaluation_steps = evaluation_steps
        self._use_async = use_async
        self._grads_to_wait = grads_to_wait
        self._sync_version_tolerance = sync_version_tolerance
        self._max_relaunch = max_relaunch
        self.ports = [find_free_port() for _ in range(num_ps)]
        self._procs = {}
        self._relaunches = {}
        self._stopped = threading.Event()
        self._lock = threading.Lock()

    @property
    def addrs(self):
        return ",".join("localhost:%d" % p for p in self.ports)

    def _args(self, ps_id, restore):
        args = [
            "--port", str(self.ports[ps_id]),
            "--ps_id", str(ps_id),
            "--num_ps", str(self.num_ps),
            "--opt_type", self._opt_type,
            "--opt_args", self._opt_args,
            "--use_async", str(self._use_async),
            "--grads_to_wait", str(self._grads_to_wait),
            "--sync_version_tolerance", str(self._sync_version_tolerance),
            "--evaluation_steps", str(self._evaluation_steps),
        ]
        if self._master_addr:
            args += ["--master_addr", self._master_addr]
        if self._checkpoint_dir:
            args += [
                "--checkpoint_dir", self._checkpoint_dir,
                "--checkpoint_steps", str(self._checkpoint_steps),
            ]
            if restore:
                args += ["--checkpoint_dir_for_init",
                         self._checkpoint_dir]
        return args

    def _launch(self, ps_id, restore=False):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        with self._lock:
            if self._stopped.is_set():
                return
            proc = subprocess.Popen(
                [sys.executable, "-m", "elasticdl_tpu.ps.server"]
                + self._args(ps_id, restore),
                env=env,
            )
            self._procs[ps_id] = proc
        logger.info("launched PS %d on port %d (restore=%s)",
                    ps_id, self.ports[ps_id], restore)
        threading.Thread(
            target=self._watch, args=(ps_id, proc),
            name="ps-watch-%d" % ps_id, daemon=True,
        ).start()

    def _watch(self, ps_id, proc):
        code = proc.wait()
        if self._stopped.is_set():
            return
        count = self._relaunches.get(ps_id, 0)
        if count >= self._max_relaunch:
            logger.error("PS %d died (code %s); relaunch budget spent",
                         ps_id, code)
            return
        self._relaunches[ps_id] = count + 1
        logger.warning("PS %d died (code %s); relaunching with restore",
                       ps_id, code)
        self._launch(ps_id, restore=bool(self._checkpoint_dir))

    def start(self):
        for ps_id in range(self.num_ps):
            self._launch(ps_id)

    def stop(self):
        # Flag first under the lock so no in-flight _watch relaunch can
        # spawn an orphan after we start terminating.
        with self._lock:
            self._stopped.set()
            procs = list(self._procs.values())
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
