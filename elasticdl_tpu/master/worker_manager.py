"""Elastic worker lifecycle management.

The TPU-native analog of the reference's pod manager
(elasticdl/python/master/pod_manager.py:207-674): launch workers, watch
their lifecycle events, relaunch failures/preemptions with *fresh* worker
ids, and notify observers (task re-queue, rendezvous refresh).  Backends
plug in under one interface:

 - ProcessWorkerBackend: workers are local subprocesses (tests and
   single-host multi-process jobs).  Preemption drills kill processes.
 - TPU-VM/k8s backends slot in here later with the same event surface.
"""

import os
import signal
import subprocess
import sys
import threading

from elasticdl_tpu.master import worker_state as ws
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class WorkerHandle:
    def __init__(self, worker_id, backend_ref, slot=None):
        self.worker_id = worker_id
        self.backend_ref = backend_ref   # backend-specific (process, pod name)
        # The stable "slot" a worker occupies across relaunches: worker 0
        # dies, worker 4 replaces it, but both fill slot 0 — services and
        # priority classes follow the slot, not the ever-increasing id.
        self.slot = worker_id if slot is None else slot
        self.status = ws.INIT
        self.relaunch_count = 0
        self.relaunch_pending = False


class ProcessWorkerBackend:
    """Workers as local subprocesses of `python -m elasticdl_tpu.worker.main`."""

    def __init__(self, worker_args=None, env=None):
        self._worker_args = worker_args or []
        self._env = env or {}

    def launch(self, worker_id, master_addr, slot=None, extra_env=None):
        del slot  # process workers have no service to re-point
        env = dict(os.environ)
        env.update(self._env)
        env.update(extra_env or {})
        env["MASTER_ADDR"] = master_addr
        env["WORKER_ID"] = str(worker_id)
        # Workers in drills run on CPU so N of them fit on one host.
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.setdefault("ELASTICDL_TPU_PLATFORM", env["JAX_PLATFORMS"])
        proc = subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.worker.main"]
            + list(self._worker_args),
            env=env,
        )
        return proc

    def wait(self, ref):
        return ref.wait()

    def kill(self, ref, force=False):
        try:
            ref.send_signal(signal.SIGKILL if force else signal.SIGTERM)
        except ProcessLookupError:
            pass

    def is_alive(self, ref):
        return ref.poll() is None


class WorkerManager:
    def __init__(
        self,
        backend,
        num_workers,
        max_relaunch_count=3,
        relaunch_on_failure=True,
        cluster_env_fn=None,
    ):
        self._backend = backend
        self._num_workers = num_workers
        self._max_relaunch = max_relaunch_count
        self._relaunch_on_failure = relaunch_on_failure
        # Optional foreign-runtime cluster-spec hook: (worker_id, slot)
        # -> {env} injected into every (re)launch, e.g. a TF_CONFIG
        # built by cluster_spec_env.make_tf_config_fn (reference
        # pod_manager.py:405-422).
        self._cluster_env_fn = cluster_env_fn
        self._master_addr = None
        self._lock = threading.Lock()
        self._workers = {}          # worker_id -> WorkerHandle
        self._next_worker_id = 0
        self._exit_callbacks = []   # fn(worker_id, should_relaunch)
        self._start_callbacks = []  # fn(worker_id)
        self._watchers = []
        self._stopped = threading.Event()
        self._preempted = set()     # worker ids killed by preemption drill

    # -- wiring -------------------------------------------------------------

    def set_master_addr(self, addr):
        with self._lock:
            self._master_addr = addr

    def add_exit_callback(self, fn):
        self._exit_callbacks.append(fn)

    def add_start_callback(self, fn):
        self._start_callbacks.append(fn)

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        for _ in range(self._num_workers):
            self._launch_worker()

    def _launch_worker(self, slot=None):
        with self._lock:
            worker_id = self._next_worker_id
            self._next_worker_id += 1
            kwargs = {}
            if self._cluster_env_fn is not None:
                kwargs["extra_env"] = self._cluster_env_fn(
                    worker_id, worker_id if slot is None else slot
                )
            ref = self._backend.launch(
                worker_id, self._master_addr, slot=slot, **kwargs
            )
            handle = WorkerHandle(worker_id, ref, slot=slot)
            handle.status = ws.PENDING
            self._workers[worker_id] = handle
        logger.info("launched worker %d", worker_id)
        watcher = threading.Thread(
            target=self._watch_worker, args=(handle,),
            name="worker-watch-%d" % worker_id, daemon=True,
        )
        watcher.start()
        self._watchers.append(watcher)
        for fn in self._start_callbacks:
            fn(worker_id)
        return worker_id

    def _watch_worker(self, handle):
        code = self._backend.wait(handle.backend_ref)
        if self._stopped.is_set():
            return
        with self._lock:
            was_preempted = handle.worker_id in self._preempted
            self._preempted.discard(handle.worker_id)
        if code == 0:
            event = ws.EV_EXIT_0
            handle.status = ws.RUNNING  # exit implies it ran
        elif was_preempted or code in (-signal.SIGTERM, -signal.SIGKILL,
                                       143):
            # 143 = the worker's graceful-preemption exit (it caught
            # SIGTERM, checkpointed, and asked to be relaunched).
            # A raw SIGKILL is ambiguous for local processes: kernel OOM
            # kills and external preemption both yield -9.  We classify it
            # as preemption (the common case on preemptible TPU hosts);
            # the relaunch budget still bounds an OOM crash-loop.
            # Containerized backends report exit 137 and hit EV_OOM below.
            event = ws.EV_PREEMPTED
        elif code == 137:
            event = ws.EV_OOM
        else:
            event = ws.EV_EXIT_ERR
        flow = ws.get_flow(
            handle.status if handle.status != ws.PENDING else ws.RUNNING,
            event,
        )
        if flow is None:
            logger.warning(
                "worker %d: no flow for (%s, %s)",
                handle.worker_id, handle.status, event,
            )
            return
        handle.status = flow.to_status
        should_relaunch = (
            flow.should_relaunch
            and self._relaunch_on_failure
            and handle.relaunch_count < self._max_relaunch
        )
        handle.relaunch_pending = should_relaunch
        logger.info(
            "worker %d exited code=%s event=%s -> %s relaunch=%s",
            handle.worker_id, code, event, handle.status, should_relaunch,
        )
        for fn in self._exit_callbacks:
            fn(handle.worker_id, should_relaunch)
        if should_relaunch and not self._stopped.is_set():
            new_id = self._launch_worker(slot=handle.slot)
            with self._lock:
                self._workers[new_id].relaunch_count = (
                    handle.relaunch_count + 1
                )
        handle.relaunch_pending = False

    # -- control ------------------------------------------------------------

    def preempt_worker(self, worker_id, force=True):
        """Kill a worker as if the platform preempted it (drill hook)."""
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None:
                return False
            self._preempted.add(worker_id)
            # Mark before killing so all_workers_done() can't observe a
            # dead-but-not-yet-relaunched window and abort the job.
            handle.relaunch_pending = True
        self._backend.kill(handle.backend_ref, force=force)
        return True

    def remove_worker(self, worker_id):
        """Master-initiated removal (task-timeout watchdog)."""
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None:
                return False
            self._preempted.add(worker_id)  # treat as relaunchable
            handle.relaunch_pending = True
        self._backend.kill(handle.backend_ref, force=True)
        return True

    def live_worker_ids(self):
        with self._lock:
            return [
                wid for wid, h in self._workers.items()
                if self._backend.is_alive(h.backend_ref)
            ]

    def all_workers_exited(self):
        with self._lock:
            return all(
                not self._backend.is_alive(h.backend_ref)
                for h in self._workers.values()
            )

    def all_workers_done(self):
        """True when every worker is dead and no relaunch is pending —
        the job cannot make further progress without intervention."""
        with self._lock:
            return all(
                not self._backend.is_alive(h.backend_ref)
                and not h.relaunch_pending
                for h in self._workers.values()
            )

    def stop(self):
        self._stopped.set()
        with self._lock:
            handles = list(self._workers.values())
        for handle in handles:
            if self._backend.is_alive(handle.backend_ref):
                self._backend.kill(handle.backend_ref, force=True)
