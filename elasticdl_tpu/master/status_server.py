"""Master HTTP status endpoint — the operator observability surface.

The reference surfaces job state through logs and the k8s API (pod
phases, the job monitor); this gives operators and probes a direct
pull surface on the master itself:

  GET /healthz   -> 200 "ok" (liveness/readiness probe target)
  GET /status    -> JSON: task counts (todo/doing/completed/failed,
                    epoch), live workers, rendezvous epoch + world,
                    worker exec counters, per-worker training telemetry
  GET /metrics   -> the same numbers in Prometheus text exposition
                    format (elasticdl_tasks_todo, ..._completed{type=},
                    elasticdl_workers_live, elasticdl_rendezvous_epoch)
  GET /tracez    -> the process flight recorder (utils/tracing.py);
                    ?fmt=chrome renders Chrome trace-event JSON for
                    Perfetto (docs/observability.md)
  GET /alertz    -> the SLO watchdog's live rule table
                    (utils/slo.py: value vs threshold, ok, breach
                    episodes)
  GET /profilez?secs=N -> capture a jax.profiler trace for N seconds
                    into $ELASTICDL_TRACE_DIR; the reply (and a
                    profile.capture flight-recorder event) carries the
                    capture dir + current trace id, so a Perfetto
                    profile links to its /tracez trace

Stdlib-only (ThreadingHTTPServer), read-only, zero coupling into the
control plane beyond the objects it snapshots.  Enabled with
``--status_port`` (master flag); port 0 picks a free one.

The Prometheus renderers live in ``utils/prom.py`` (single escaping /
labels implementation for the whole system); this module re-exports
them so historical imports keep working.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from elasticdl_tpu.utils import slo as slo_mod
from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.logging import get_logger
from elasticdl_tpu.utils.prom import (  # noqa: F401  (re-exported API)
    fleet_to_prometheus,
    multitenant_to_prometheus,
    prometheus_line,
    serving_to_prometheus,
    to_prometheus,
)

logger = get_logger(__name__)


def collect_status(task_manager, worker_manager=None,
                   rendezvous_server=None, servicer=None):
    status = {"tasks": task_manager.counts(),
              "finished": task_manager.finished()}
    if worker_manager is not None:
        status["workers"] = {
            "live": sorted(worker_manager.live_worker_ids()),
        }
    if rendezvous_server is not None:
        status["rendezvous"] = {
            "epoch": rendezvous_server.rendezvous_id,
            "world": rendezvous_server.world,
        }
    if servicer is not None:
        status["exec_counters"] = dict(servicer.worker_exec_counters)
        telemetry = servicer.telemetry()
        if telemetry["workers"]:
            # Per-worker steps/s, sync_fraction, push staleness,
            # fused-window stats piggybacked on the coalesced progress
            # RPCs — the resize-controller sensor input (ROADMAP 5).
            status["telemetry"] = telemetry
        rpc_hists = servicer.rpc_histograms()
        if rpc_hists:
            # Master RPC handle-time histograms (get_task / progress /
            # result reports) — rendered as native Prometheus
            # histograms by utils/prom.py.
            status["rpc_hists"] = rpc_hists
        ps_state = servicer.ps_state()
        if ps_state:
            # PS recovery plane (docs/ps_recovery.md): per-shard
            # generation/durable version plus the cross-shard commit
            # mark — the version a PS restore would come back at.
            status["ps"] = {
                "shards": ps_state,
                "commit_mark": servicer.ps_commit_mark(),
            }
    slo = slo_mod.slo_section()
    if slo is not None:
        status["slo"] = slo
    return status


class HttpStatusServer:
    """Generic /healthz /status /metrics /tracez server over a
    collect_fn (returns the JSON-able status dict) and a prom_fn
    (renders it as Prometheus text).  The master's StatusServer and
    the PS's metrics endpoint are both instances."""

    def __init__(self, collect_fn, prom_fn, port=0, host="0.0.0.0"):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("status: " + fmt, *args)

            def _reply(self, code, body, content_type):
                payload = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if self.path == "/healthz":
                    return self._reply(200, "ok\n", "text/plain")
                if tracing.is_tracez_path(self.path):
                    # Live flight-recorder query: independent of
                    # collect_fn so a wedged control plane can still
                    # be traced.
                    return self._reply(
                        200, tracing.tracez_body(self.path),
                        "application/json")
                if slo_mod.is_alertz_path(self.path):
                    # The SLO watchdog surface — also independent of
                    # collect_fn (evaluation reads its own sources).
                    return self._reply(
                        200, slo_mod.alertz_body(),
                        "application/json")
                if tracing.is_profilez_path(self.path):
                    # On-demand jax profiler capture; blocks THIS
                    # request thread for the capture window only.
                    return self._reply(
                        200, tracing.profilez_body(self.path),
                        "application/json")
                try:
                    status = collect_fn()
                except Exception as e:  # noqa: BLE001 — a probe must
                    # get a 500, not a dropped connection
                    return self._reply(500, "error: %s\n" % e,
                                       "text/plain")
                if self.path == "/status":
                    return self._reply(200, json.dumps(status),
                                       "application/json")
                if self.path == "/metrics":
                    return self._reply(
                        200, prom_fn(status),
                        "text/plain; version=0.0.4")
                return self._reply(404, "unknown path %s\n" % self.path,
                                   "text/plain")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="status-http",
            daemon=True,
        )

    def start(self):
        self._thread.start()
        logger.info("status server on port %d "
                    "(/healthz /status /metrics /tracez)", self.port)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class StatusServer(HttpStatusServer):
    def __init__(self, task_manager, worker_manager=None,
                 rendezvous_server=None, servicer=None, port=0,
                 host="0.0.0.0"):
        super().__init__(
            lambda: collect_status(
                task_manager, worker_manager=worker_manager,
                rendezvous_server=rendezvous_server,
                servicer=servicer,
            ),
            to_prometheus, port=port, host=host,
        )


def collect_multitenant_status(registry, worker_manager=None):
    """The multi-tenant master's /status payload: the scheduler view
    (pool, admission queue, assignment map, decision counters) plus a
    per-job section reusing the single-job surfaces — task counts, the
    per-job telemetry aggregate (the resize controller's sensor input)
    and the job's rendezvous epoch (docs/scheduler.md)."""
    status = {"sched": registry.status(), "jobs": {}}
    for job in registry.jobs():
        entry = {
            "id": job.job_id,
            "state": job.state,
            "tasks": job.task_manager.counts(),
            "finished": job.task_manager.finished(),
            "telemetry": job.servicer.telemetry(),
            "exec_counters": dict(job.servicer.worker_exec_counters),
        }
        if job.rendezvous is not None:
            entry["rendezvous"] = {
                "epoch": job.rendezvous.rendezvous_id,
                "world": job.rendezvous.world,
            }
        status["jobs"][job.spec.name] = entry
    if worker_manager is not None:
        status["workers"] = {
            "live": sorted(worker_manager.live_worker_ids()),
        }
    slo = slo_mod.slo_section()
    if slo is not None:
        status["slo"] = slo
    return status


class MultiTenantStatusServer(HttpStatusServer):
    def __init__(self, registry, worker_manager=None, port=0,
                 host="0.0.0.0"):
        super().__init__(
            lambda: collect_multitenant_status(
                registry, worker_manager=worker_manager,
            ),
            multitenant_to_prometheus, port=port, host=host,
        )
