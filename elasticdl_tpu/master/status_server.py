"""Master HTTP status endpoint — the operator observability surface.

The reference surfaces job state through logs and the k8s API (pod
phases, the job monitor); this gives operators and probes a direct
pull surface on the master itself:

  GET /healthz   -> 200 "ok" (liveness/readiness probe target)
  GET /status    -> JSON: task counts (todo/doing/completed/failed,
                    epoch), live workers, rendezvous epoch + world,
                    worker exec counters
  GET /metrics   -> the same numbers in Prometheus text exposition
                    format (elasticdl_tasks_todo, ..._completed{type=},
                    elasticdl_workers_live, elasticdl_rendezvous_epoch)

Stdlib-only (ThreadingHTTPServer), read-only, zero coupling into the
control plane beyond the objects it snapshots.  Enabled with
``--status_port`` (master flag); port 0 picks a free one.

This module is also the home of every Prometheus exposition renderer in
the system — the PS status page, the serving replicas' /metrics
(``serving_to_prometheus``), and the fleet router's /metrics
(``fleet_to_prometheus``) all share ``prometheus_line``, so the drills
and a real scraper read ONE format across the control plane, the PS
tier, and the serving tier.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def collect_status(task_manager, worker_manager=None,
                   rendezvous_server=None, servicer=None):
    status = {"tasks": task_manager.counts(),
              "finished": task_manager.finished()}
    if worker_manager is not None:
        status["workers"] = {
            "live": sorted(worker_manager.live_worker_ids()),
        }
    if rendezvous_server is not None:
        status["rendezvous"] = {
            "epoch": rendezvous_server.rendezvous_id,
            "world": rendezvous_server.world,
        }
    if servicer is not None:
        status["exec_counters"] = dict(servicer.worker_exec_counters)
        ps_state = servicer.ps_state()
        if ps_state:
            # PS recovery plane (docs/ps_recovery.md): per-shard
            # generation/durable version plus the cross-shard commit
            # mark — the version a PS restore would come back at.
            status["ps"] = {
                "shards": ps_state,
                "commit_mark": servicer.ps_commit_mark(),
            }
    return status


def prometheus_line(metric, value, **labels):
    """One exposition-format sample line — THE renderer both the
    master's and the PS's /metrics share."""
    label_str = ""
    if labels:
        label_str = "{%s}" % ",".join(
            '%s="%s"' % kv for kv in sorted(labels.items()))
    return "%s%s %s" % (metric, label_str, value)


def to_prometheus(status):
    lines = []

    def gauge(metric, value, **labels):
        lines.append(prometheus_line(metric, value, **labels))

    tasks = status["tasks"]
    gauge("elasticdl_tasks_todo", tasks["todo"])
    gauge("elasticdl_tasks_doing", tasks["doing"])
    gauge("elasticdl_data_epoch", tasks["epoch"])
    for kind in ("completed", "failed"):
        for task_type, count in tasks[kind].items():
            gauge("elasticdl_tasks_%s" % kind, count,
                  type=str(task_type))
    gauge("elasticdl_job_finished", int(status["finished"]))
    if "workers" in status:
        gauge("elasticdl_workers_live", len(status["workers"]["live"]))
    if "rendezvous" in status:
        gauge("elasticdl_rendezvous_epoch",
              status["rendezvous"]["epoch"])
        gauge("elasticdl_rendezvous_world_size",
              len(status["rendezvous"]["world"]))
    for name, value in status.get("exec_counters", {}).items():
        gauge("elasticdl_worker_counter", value, name=name)
    if "ps" in status:
        gauge("elasticdl_ps_commit_mark", status["ps"]["commit_mark"])
        for ps_id, shard in sorted(status["ps"]["shards"].items()):
            gauge("elasticdl_ps_shard_generation",
                  shard["generation"], ps_id=str(ps_id))
            gauge("elasticdl_ps_shard_durable_version",
                  shard["durable_version"], ps_id=str(ps_id))
    return "\n".join(lines) + "\n"


def serving_to_prometheus(status):
    """Serving-replica /metrics renderer (serving/server.py) — mirrors
    the master's ``elasticdl_ps_commit_mark`` convention so the fleet
    router, the drills, and a Prometheus scraper read ONE format across
    the control plane and the serving tier.

    ``status``: {"draining": bool, "models": {name: endpoint.stats()}}.
    """
    lines = [prometheus_line("elasticdl_serving_draining",
                             int(status.get("draining", False)))]
    for name, stats in sorted(status.get("models", {}).items()):
        counters = stats.get("counters", {})

        def gauge(metric, value, _model=name):
            lines.append(prometheus_line(metric, value, model=_model))

        gauge("elasticdl_serving_version", stats.get("version", 0))
        gauge("elasticdl_serving_requests",
              counters.get("batcher.requests", 0))
        gauge("elasticdl_serving_batches",
              counters.get("batcher.batches", 0))
        occupancy = stats.get("mean_batch_occupancy")
        if occupancy is not None:
            gauge("elasticdl_serving_occupancy", occupancy)
        wait = stats.get("timing", {}).get("batcher.queue_wait")
        if wait:
            gauge("elasticdl_serving_queue_wait_ms",
                  1e3 * wait["mean_s"])
        cache = stats.get("emb_cache")
        if cache:
            gauge("elasticdl_serving_emb_cache_bytes", cache["bytes"])
            gauge("elasticdl_serving_emb_cache_rows", cache["rows"])
            gauge("elasticdl_serving_emb_cache_evicted_rows",
                  cache["evicted_rows"])
            if cache.get("hit_ratio") is not None:
                gauge("elasticdl_serving_emb_cache_hit_ratio",
                      round(cache["hit_ratio"], 6))
    return "\n".join(lines) + "\n"


def fleet_to_prometheus(status):
    """Router /metrics renderer (serving/router.py): the FLEET view —
    committed version, per-replica health/load/version, routing
    counters — in the same exposition format as everything else.

    ``status``: the router's ``fleet_status()`` dict.
    """
    lines = [
        prometheus_line("elasticdl_fleet_committed_version",
                        status.get("committed_version", 0)),
        prometheus_line("elasticdl_fleet_replicas_healthy",
                        sum(1 for r in status.get("replicas", {})
                            .values() if r.get("healthy"))),
        prometheus_line("elasticdl_fleet_replicas_total",
                        len(status.get("replicas", {}))),
    ]
    for addr, rep in sorted(status.get("replicas", {}).items()):
        def gauge(metric, value, _addr=addr):
            lines.append(prometheus_line(metric, value, replica=_addr))

        gauge("elasticdl_fleet_replica_healthy",
              int(rep.get("healthy", False)))
        gauge("elasticdl_fleet_replica_serving_version",
              rep.get("serving_version", 0))
        gauge("elasticdl_fleet_replica_inflight",
              rep.get("inflight", 0))
        if rep.get("queue_wait_ms") is not None:
            gauge("elasticdl_fleet_replica_queue_wait_ms",
                  rep["queue_wait_ms"])
    for name, value in sorted(status.get("counters", {}).items()):
        lines.append(prometheus_line("elasticdl_fleet_router_counter",
                                     value, name=name))
    return "\n".join(lines) + "\n"


class HttpStatusServer:
    """Generic /healthz /status /metrics server over a collect_fn
    (returns the JSON-able status dict) and a prom_fn (renders it as
    Prometheus text).  The master's StatusServer and the PS's metrics
    endpoint are both instances."""

    def __init__(self, collect_fn, prom_fn, port=0, host="0.0.0.0"):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("status: " + fmt, *args)

            def _reply(self, code, body, content_type):
                payload = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if self.path == "/healthz":
                    return self._reply(200, "ok\n", "text/plain")
                try:
                    status = collect_fn()
                except Exception as e:  # noqa: BLE001 — a probe must
                    # get a 500, not a dropped connection
                    return self._reply(500, "error: %s\n" % e,
                                       "text/plain")
                if self.path == "/status":
                    return self._reply(200, json.dumps(status),
                                       "application/json")
                if self.path == "/metrics":
                    return self._reply(
                        200, prom_fn(status),
                        "text/plain; version=0.0.4")
                return self._reply(404, "unknown path %s\n" % self.path,
                                   "text/plain")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="status-http",
            daemon=True,
        )

    def start(self):
        self._thread.start()
        logger.info("status server on port %d "
                    "(/healthz /status /metrics)", self.port)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class StatusServer(HttpStatusServer):
    def __init__(self, task_manager, worker_manager=None,
                 rendezvous_server=None, servicer=None, port=0,
                 host="0.0.0.0"):
        super().__init__(
            lambda: collect_status(
                task_manager, worker_manager=worker_manager,
                rendezvous_server=rendezvous_server,
                servicer=servicer,
            ),
            to_prometheus, port=port, host=host,
        )
