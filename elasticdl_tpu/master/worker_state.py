"""Declarative worker lifecycle state machine.

Parity with the reference's pod state-flow table
(elasticdl/python/master/pod_state.py:28-118): transitions are data, not
code, so backends (local process, k8s/TPU-VM) share one lifecycle and the
relaunch decision is auditable.
"""

from collections import namedtuple

# Worker statuses
INIT = "Init"
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"
DELETED = "Deleted"

# Events
EV_LAUNCHED = "launched"
EV_STARTED = "started"
EV_EXIT_0 = "exit_ok"
EV_EXIT_ERR = "exit_err"
EV_PREEMPTED = "preempted"   # external kill (the TPU-preemption analog)
EV_OOM = "oom_killed"        # never relaunched (reference pod_manager.py:102-115)
EV_REMOVED = "removed"       # master-initiated removal (timeout watchdog)

Flow = namedtuple("Flow", ["from_status", "event", "to_status",
                           "should_relaunch"])

STATE_FLOWS = [
    Flow(INIT, EV_LAUNCHED, PENDING, False),
    Flow(PENDING, EV_STARTED, RUNNING, False),
    Flow(PENDING, EV_EXIT_ERR, FAILED, True),
    Flow(PENDING, EV_PREEMPTED, DELETED, True),
    Flow(RUNNING, EV_EXIT_0, SUCCEEDED, False),
    Flow(RUNNING, EV_EXIT_ERR, FAILED, True),
    Flow(RUNNING, EV_PREEMPTED, DELETED, True),
    Flow(RUNNING, EV_OOM, FAILED, False),
    Flow(RUNNING, EV_REMOVED, DELETED, True),
    Flow(PENDING, EV_REMOVED, DELETED, True),
]

_INDEX = {(f.from_status, f.event): f for f in STATE_FLOWS}


def get_flow(from_status, event):
    """Return the matching Flow or None for ignorable transitions."""
    return _INDEX.get((from_status, event))
