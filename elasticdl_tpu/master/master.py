"""Master orchestrator.

Composes the control-plane components and runs the job to completion
(parity: elasticdl/python/master/master.py:32-135).  The worker manager is
optional — in "wrap your own loop" deployments workers are launched
externally and only the gRPC services run here.
"""

import threading
import time

from elasticdl_tpu.master.servicer import MasterServicer, create_master_service
from elasticdl_tpu.utils import slo
from elasticdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class Master:
    def __init__(
        self,
        task_manager,
        rendezvous_server=None,
        evaluation_service=None,
        worker_manager=None,
        port=0,
        poll_secs=1.0,
        journal=None,
        interceptors=None,
    ):
        self.task_manager = task_manager
        self.rendezvous_server = rendezvous_server
        self.evaluation_service = evaluation_service
        self.worker_manager = worker_manager
        self._port = port
        self._poll_secs = poll_secs
        self._server = None
        # Crash-restart recovery: the job-state journal (owned by
        # main, threaded into every journaling component) and optional
        # server interceptors (fault injection for drills).
        self.journal = journal
        self._interceptors = interceptors
        self.port = None
        # How managed workers dial back.  None = "localhost:<port>"
        # (process backend).  A k8s master advertises its service DNS
        # name instead; "%d" if present is filled with the bound port.
        self.advertise_addr = None
        self._stop_requested = threading.Event()
        self.servicer = MasterServicer(
            task_manager,
            rendezvous_server=rendezvous_server,
            evaluation_service=evaluation_service,
            worker_manager=worker_manager,
            journal=journal,
        )

    def prepare(self):
        # Elasticity wiring: a dead worker's tasks go back on the queue and
        # the collective world is refreshed (reference
        # pod_event_callbacks.py:80-115).
        if self.worker_manager is not None:
            self.worker_manager.add_exit_callback(self._on_worker_exit)
        self.task_manager.add_worker_timeout_callback(
            self._on_worker_timeout
        )
        self.task_manager.start()
        self._server, self.port = create_master_service(
            self.servicer, port=self._port,
            interceptors=self._interceptors,
        )
        if self.worker_manager is not None:
            addr = self.advertise_addr or "localhost:%d"
            if "%d" in addr:
                addr = addr % self.port
            self.worker_manager.set_master_addr(addr)
            self.worker_manager.start()

    def _on_worker_exit(self, worker_id, should_relaunch):
        self.task_manager.recover_tasks(worker_id)
        if self.rendezvous_server is not None:
            self.rendezvous_server.remove_worker("worker-%d" % worker_id)

    def _on_worker_timeout(self, worker_id):
        if self.worker_manager is not None:
            self.worker_manager.remove_worker(worker_id)
        if self.rendezvous_server is not None:
            self.rendezvous_server.remove_worker("worker-%d" % worker_id)

    def run(self):
        """Block until all tasks are done (and managed workers exited)."""
        stalled_polls = 0
        try:
            while not self._stop_requested.is_set():
                if self.task_manager.finished():
                    if (
                        self.worker_manager is None
                        or self.worker_manager.all_workers_exited()
                    ):
                        counts = self.task_manager.counts()
                        lost = sum(counts["failed"].values())
                        if lost:
                            # Permanently-failed tasks mean dropped data:
                            # the job ran to the end but did not do what
                            # was asked — surface that in the exit code
                            # rather than reporting silent success.
                            logger.error(
                                "job finished with %d permanently "
                                "failed task(s): %s", lost, counts,
                            )
                            return 1
                        logger.info("job finished: %s", counts)
                        break
                elif (
                    self.worker_manager is not None
                    and self.worker_manager.all_workers_done()
                ):
                    # Require consecutive observations: a watcher thread may
                    # not have processed a fresh exit yet (relaunch_pending
                    # is only set once the exit event is handled).
                    stalled_polls += 1
                    if stalled_polls >= 3:
                        logger.error(
                            "all workers failed permanently with tasks "
                            "remaining: %s", self.task_manager.counts(),
                        )
                        return 1
                else:
                    stalled_polls = 0
                # Straggler sweep + SLO evaluation ride the poll
                # cadence (the single-job analog of the multi-tenant
                # ResizeController tick): cross-worker step-time skew
                # is flagged and the default straggler rule can breach
                # without any external scraper driving it.
                if self.servicer is not None:
                    self.servicer.straggler_sweep()
                    if slo.default_watchdog().rule_count:
                        slo.default_watchdog().evaluate()
                time.sleep(self._poll_secs)
        finally:
            self.stop()
        return 0

    def stop(self):
        self._stop_requested.set()
        self.task_manager.stop()
        if self.worker_manager is not None:
            self.worker_manager.stop()
        if self._server is not None:
            self._server.stop(grace=1)
            self._server = None
        if self.journal is not None:
            # Flush any buffered progress events; the journal stays
            # open for late lifecycle appends (close is owned by main).
            self.journal.flush()
